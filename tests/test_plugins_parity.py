"""Kernel-vs-oracle parity: NodeResourcesFit + BalancedAllocation.

Every kernel output (filter reasons, raw scores) must equal the pure-Python
oracle, which replicates upstream Go plugin code exactly (int64 / float64).
"""

import numpy as np
import pytest

from ksim_tpu.engine import Engine, ScoredPlugin
from ksim_tpu.plugins import oracle
from ksim_tpu.plugins.noderesources import (
    NodeResourcesBalancedAllocation,
    NodeResourcesFit,
)
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod, random_cluster


from ksim_tpu.engine.profiles import default_plugins


def build_engine(nodes, pods, queue=None, record="full"):
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue or ())
    return feats, Engine(feats, default_plugins(feats), record=record)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_batch_parity_random_clusters(seed):
    nodes, pods = random_cluster(seed, n_nodes=13, n_pods=29)
    feats, eng = build_engine(nodes, pods)
    res = eng.evaluate_batch()

    infos = oracle.build_node_infos(nodes, pods)
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    assert len(queue) == feats.pods.count

    fit = NodeResourcesFit(feats.resources)
    from ksim_tpu.plugins.nodeaffinity import NodeAffinity
    from ksim_tpu.plugins.nodeunschedulable import NodeUnschedulable
    from ksim_tpu.plugins.tainttoleration import TaintToleration

    unsched = NodeUnschedulable()
    taint = TaintToleration(feats.aux["taints"])
    aff = NodeAffinity()
    uns_f = res.filter_plugin_names.index("NodeUnschedulable")
    fit_f = res.filter_plugin_names.index("NodeResourcesFit")
    tnt_f = res.filter_plugin_names.index("TaintToleration")
    aff_f = res.filter_plugin_names.index("NodeAffinity")
    fit_s = res.plugin_names.index("NodeResourcesFit")
    bal_s = res.plugin_names.index("NodeResourcesBalancedAllocation")
    tnt_s = res.plugin_names.index("TaintToleration")
    aff_s = res.plugin_names.index("NodeAffinity")

    for pi, pod in enumerate(queue):
        for ni, info in enumerate(infos):
            key = (pod["metadata"]["name"], info["name"])
            want_reasons = oracle.fit_filter(pod, info)
            got_reasons = fit.decode_reasons(int(res.reason_bits[pi, fit_f, ni]))
            assert got_reasons == want_reasons, key
            want_uns = oracle.node_unschedulable_filter(pod, info)
            got_uns = unsched.decode_reasons(int(res.reason_bits[pi, uns_f, ni]))
            assert got_uns == want_uns, key
            want_tnt = oracle.taint_toleration_filter(pod, info)
            got_tnt = taint.decode_reasons(int(res.reason_bits[pi, tnt_f, ni]))
            assert got_tnt == want_tnt, key
            want_aff = oracle.node_affinity_filter(pod, info)
            got_aff = aff.decode_reasons(int(res.reason_bits[pi, aff_f, ni]))
            assert got_aff == want_aff, key
            assert int(res.scores[pi, fit_s, ni]) == oracle.least_allocated_score(pod, info)
            assert int(res.scores[pi, bal_s, ni]) == oracle.balanced_allocation_score(pod, info)
            assert int(res.scores[pi, tnt_s, ni]) == oracle.taint_toleration_score(pod, info)
            assert int(res.scores[pi, aff_s, ni]) == oracle.node_affinity_score(pod, info)


def test_fit_filter_messages():
    nodes = [make_node("small", cpu="1", memory="1Gi", pods=1)]
    pods = [make_pod("bound", cpu="500m", memory="512Mi", node_name="small")]
    big = make_pod("big", cpu="2", memory="2Gi")
    feats, eng = build_engine(nodes, pods, queue=[big])
    res = eng.evaluate_batch()
    fit = NodeResourcesFit(feats.resources)
    fit_f = res.filter_plugin_names.index("NodeResourcesFit")
    reasons = fit.decode_reasons(int(res.reason_bits[0, fit_f, 0]))
    assert reasons == ["Too many pods", "Insufficient cpu", "Insufficient memory"]
    assert not res.feasible[0]
    assert res.selected[0] == -1


def test_fit_no_requests_only_pod_count():
    nodes = [make_node("full", cpu="1", memory="1Gi", pods=1)]
    pods = [make_pod("bound", cpu="900m", memory="1Gi", node_name="full")]
    empty = make_pod("empty", cpu=None, memory=None)
    feats, eng = build_engine(nodes, pods, queue=[empty])
    res = eng.evaluate_batch()
    fit = NodeResourcesFit(feats.resources)
    fit_f = res.filter_plugin_names.index("NodeResourcesFit")
    # Pod requests nothing: resource bits suppressed, only "Too many pods".
    assert fit.decode_reasons(int(res.reason_bits[0, fit_f, 0])) == ["Too many pods"]


def test_overcommitted_node_reports_all_checked_resources():
    # requested > allocatable on memory; pod requesting only cpu still sees
    # "Insufficient memory" (upstream: 0 > negative free is true).
    nodes = [make_node("oc", cpu="4", memory="1Gi")]
    pods = [
        make_pod("b1", cpu="1", memory="1Gi", node_name="oc"),
        make_pod("b2", cpu="1", memory="512Mi", node_name="oc"),
    ]
    q = make_pod("q", cpu="100m", memory=None)
    feats, eng = build_engine(nodes, pods, queue=[q])
    res = eng.evaluate_batch()
    fit = NodeResourcesFit(feats.resources)
    fit_f = res.filter_plugin_names.index("NodeResourcesFit")
    got = fit.decode_reasons(int(res.reason_bits[0, fit_f, 0]))
    info = oracle.build_node_infos(nodes, pods)[0]
    assert got == oracle.fit_filter(q, info) == ["Insufficient memory"]


def test_balanced_exact_integer_path():
    # f_cpu = 0.5, f_mem = 0.25 -> std = 0.125 -> score 87 (int64 floor).
    nodes = [make_node("n", cpu="2", memory="4Gi")]
    q = make_pod("q", cpu="1", memory="1Gi")
    feats, eng = build_engine(nodes, [], queue=[q])
    res = eng.evaluate_batch()
    bal_s = res.plugin_names.index("NodeResourcesBalancedAllocation")
    assert int(res.scores[0, bal_s, 0]) == 87
    info = oracle.build_node_infos(nodes, [])[0]
    assert oracle.balanced_allocation_score(q, info) == 87


def test_zero_valued_extended_resource_defeats_early_exit():
    # Upstream: a zero-valued scalar-resource key populates ScalarResources,
    # so base-resource checks still run against an overcommitted node.
    nodes = [make_node("oc", cpu="1", memory="1Gi")]
    pods = [make_pod("b", cpu="2", memory="1Gi", node_name="oc")]  # overcommit cpu
    q = make_pod("q", cpu=None, memory=None, extra_requests={"example.com/x": "0"})
    feats, eng = build_engine(nodes, pods, queue=[q])
    res = eng.evaluate_batch()
    fit = NodeResourcesFit(feats.resources)
    fit_f = res.filter_plugin_names.index("NodeResourcesFit")
    got = fit.decode_reasons(int(res.reason_bits[0, fit_f, 0]))
    info = oracle.build_node_infos(nodes, pods)[0]
    assert got == oracle.fit_filter(q, info) == ["Insufficient cpu"]


def test_match_fields_metadata_name():
    nodes = [make_node("target"), make_node("other")]
    aff = {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
        "nodeSelectorTerms": [{"matchFields": [
            {"key": "metadata.name", "operator": "In", "values": ["target"]}]}]}}}
    q = make_pod("q", affinity=aff)
    feats, eng = build_engine(nodes, [], queue=[q])
    res = eng.evaluate_batch()
    assert feats.nodes.names[int(res.selected[0])] == "target"
    infos = oracle.build_node_infos(nodes, [])
    assert oracle.node_affinity_filter(q, infos[0]) == []
    assert oracle.node_affinity_filter(q, infos[1]) != []


def test_match_fields_unsupported_key_matches_nothing():
    nodes = [make_node("n1")]
    aff = {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
        "nodeSelectorTerms": [{"matchFields": [
            {"key": "spec.foo", "operator": "Exists"}]}]}}}
    q = make_pod("q", affinity=aff)
    feats, eng = build_engine(nodes, [], queue=[q])
    res = eng.evaluate_batch()
    assert int(res.selected[0]) == -1  # term matches nothing -> unschedulable
    info = oracle.build_node_infos(nodes, [])[0]
    assert oracle.node_affinity_filter(q, info) != []
