"""Result-annotation rendering: the reference's 13-key contract."""

import json

from ksim_tpu.engine import Engine
from ksim_tpu.engine.annotations import (
    ALL_RESULT_KEYS,
    BIND_RESULT_KEY,
    FILTER_RESULT_KEY,
    FINAL_SCORE_RESULT_KEY,
    PRE_SCORE_RESULT_KEY,
    RESULT_HISTORY_KEY,
    SCORE_RESULT_KEY,
    SELECTED_NODE_KEY,
    apply_results_to_pod,
    render_pod_results,
    update_result_history,
)
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod


def run(nodes, bound, queue):
    feats = Featurizer().featurize(nodes, bound, queue_pods=queue)
    plugins = default_plugins(feats)
    eng = Engine(feats, plugins, record="full")
    return feats, plugins, eng.evaluate_batch()


def test_all_keys_present_and_json():
    nodes = [make_node("n1"), make_node("n2")]
    feats, plugins, res = run(nodes, [], [make_pod("p")])
    anno = render_pod_results(feats, plugins, res, 0)
    for key in ALL_RESULT_KEYS:
        assert key in anno, key
    for key, val in anno.items():
        if key != SELECTED_NODE_KEY:
            json.loads(val)  # every value is valid JSON


def test_filter_result_passed_and_early_exit():
    # n2 is cordoned: NodeUnschedulable (first filter) rejects, later
    # filters must have NO entry for n2 (upstream early exit).
    nodes = [make_node("n1"), make_node("n2", unschedulable=True)]
    feats, plugins, res = run(nodes, [], [make_pod("p", cpu="100m")])
    anno = render_pod_results(feats, plugins, res, 0)
    fm = json.loads(anno[FILTER_RESULT_KEY])
    assert fm["n1"]["NodeUnschedulable"] == "passed"
    assert fm["n1"]["NodeResourcesFit"] == "passed"
    assert fm["n2"]["NodeUnschedulable"] == "node(s) were unschedulable"
    assert list(fm["n2"].keys()) == ["NodeUnschedulable"]


def test_scores_only_on_feasible_nodes():
    nodes = [
        make_node("big", cpu="8"),
        make_node("big2", cpu="8"),
        make_node("tiny", cpu="100m"),
    ]
    feats, plugins, res = run(nodes, [], [make_pod("p", cpu="2")])
    anno = render_pod_results(feats, plugins, res, 0)
    sm = json.loads(anno[SCORE_RESULT_KEY])
    assert "big" in sm and "big2" in sm and "tiny" not in sm
    fm = json.loads(anno[FINAL_SCORE_RESULT_KEY])
    # finalscore = normalized x weight: TaintToleration weight 3, all nodes
    # taintless -> normalized 100 -> 300.
    assert fm["big"]["TaintToleration"] == "300"
    assert anno[SELECTED_NODE_KEY] == "big"
    assert json.loads(anno[BIND_RESULT_KEY]) == {"DefaultBinder": "success"}


def test_one_feasible_node_skips_scoring():
    # Upstream schedulePod early-returns when exactly one node passes
    # filtering: Score/PreScore never run, the recorded maps are empty,
    # but the pod is still bound to that node.
    nodes = [make_node("big", cpu="8"), make_node("tiny", cpu="100m")]
    feats, plugins, res = run(nodes, [], [make_pod("p", cpu="2")])
    anno = render_pod_results(feats, plugins, res, 0)
    assert json.loads(anno[SCORE_RESULT_KEY]) == {}
    assert json.loads(anno[FINAL_SCORE_RESULT_KEY]) == {}
    assert json.loads(anno[PRE_SCORE_RESULT_KEY]) == {}
    assert anno[SELECTED_NODE_KEY] == "big"
    assert json.loads(anno[BIND_RESULT_KEY]) == {"DefaultBinder": "success"}


def test_unschedulable_pod_has_no_selected_node():
    nodes = [make_node("tiny", cpu="100m")]
    feats, plugins, res = run(nodes, [], [make_pod("p", cpu="4")])
    anno = render_pod_results(feats, plugins, res, 0)
    assert SELECTED_NODE_KEY not in anno
    assert json.loads(anno[BIND_RESULT_KEY]) == {}
    assert json.loads(anno[SCORE_RESULT_KEY]) == {}


def test_multi_reason_message_joined():
    nodes = [make_node("small", cpu="1", memory="1Gi", pods=1)]
    bound = [make_pod("b", cpu="500m", memory="512Mi", node_name="small")]
    feats, plugins, res = run(nodes, bound, [make_pod("big", cpu="2", memory="2Gi")])
    anno = render_pod_results(feats, plugins, res, 0)
    fm = json.loads(anno[FILTER_RESULT_KEY])
    assert fm["small"]["NodeResourcesFit"] == (
        "Too many pods, Insufficient cpu, Insufficient memory"
    )


def test_result_history_appends():
    anno = {}
    update_result_history(anno, {"a": "1"})
    update_result_history(anno, {"b": "2"})
    assert json.loads(anno[RESULT_HISTORY_KEY]) == [{"a": "1"}, {"b": "2"}]


def test_apply_results_merges_and_records_history():
    nodes = [make_node("n1")]
    feats, plugins, res = run(nodes, [], [make_pod("p")])
    result = render_pod_results(feats, plugins, res, 0)
    pod_anno = {"user-key": "untouched"}
    apply_results_to_pod(pod_anno, result)
    assert pod_anno["user-key"] == "untouched"
    assert pod_anno[SELECTED_NODE_KEY] == "n1"
    hist = json.loads(pod_anno[RESULT_HISTORY_KEY])
    assert len(hist) == 1 and hist[0][SELECTED_NODE_KEY] == "n1"


def test_reserve_prebind_record_volume_binding():
    """Scheduled pods record VolumeBinding success at Reserve/PreBind
    (the default profile's only plugin at those points); a per-point
    profile disable drops it from that annotation only."""
    import json

    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore
    from tests.helpers import make_node, make_pod
    from ksim_tpu.engine.annotations import (
        PRE_BIND_RESULT_KEY,
        RESERVE_RESULT_KEY,
    )

    store = ClusterStore()
    store.create("nodes", make_node("n0"))
    store.create("pods", make_pod("p0"))
    SchedulerService(store).schedule_pending()
    annos = store.get("pods", "p0")["metadata"]["annotations"]
    assert json.loads(annos[RESERVE_RESULT_KEY]) == {"VolumeBinding": "success"}
    assert json.loads(annos[PRE_BIND_RESULT_KEY]) == {"VolumeBinding": "success"}

    store2 = ClusterStore()
    store2.create("nodes", make_node("n0"))
    store2.create("pods", make_pod("p0"))
    cfg = {"profiles": [{
        "plugins": {"reserve": {"disabled": [{"name": "VolumeBinding"}]}},
    }]}
    SchedulerService(store2, config=cfg).schedule_pending()
    annos2 = store2.get("pods", "p0")["metadata"]["annotations"]
    assert json.loads(annos2[RESERVE_RESULT_KEY]) == {}
    assert json.loads(annos2[PRE_BIND_RESULT_KEY]) == {"VolumeBinding": "success"}


def test_reason_dtype_grows_with_taint_vocab():
    """TaintToleration's reason is a 1-based taint-vocabulary INDEX; the
    engine's result-tensor downcast must widen with the vocabulary so a
    large cluster's indices don't wrap (engine/core.py _result_dtypes)."""
    import numpy as np

    from ksim_tpu.engine.core import _Program, ScoredPlugin
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer
    from tests.helpers import make_node, make_pod

    def eval_bits_dtype(n_taints):
        nodes = []
        for i in range(max(n_taints, 2)):
            n = make_node(f"n{i}")
            n["spec"]["taints"] = [
                {"key": f"k{i}", "value": "v", "effect": "NoSchedule"}
            ]
            nodes.append(n)
        feats = Featurizer().featurize(nodes, [], queue_pods=[make_pod("p")])
        plugins = default_plugins(feats)
        prog = _Program(tuple(plugins), "full")
        bits_dtype, _final = prog._result_dtypes()
        return np.dtype(bits_dtype)

    assert eval_bits_dtype(4) == np.int8
    assert eval_bits_dtype(200) == np.int16
