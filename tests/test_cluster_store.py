"""ClusterStore CRUD / watch / restore semantics."""

import pytest

from ksim_tpu.errors import ConflictError, NotFoundError
from ksim_tpu.state.cluster import ADDED, DELETED, MODIFIED, ClusterStore
from tests.helpers import make_node, make_pod


def test_crud_roundtrip():
    s = ClusterStore()
    s.create("nodes", make_node("n1"))
    got = s.get("nodes", "n1")
    assert got["metadata"]["name"] == "n1"
    assert got["metadata"]["resourceVersion"]
    with pytest.raises(ConflictError):
        s.create("nodes", make_node("n1"))
    s.delete("nodes", "n1")
    with pytest.raises(NotFoundError):
        s.get("nodes", "n1")


def test_namespaced_listing():
    s = ClusterStore()
    s.create("pods", make_pod("p1", namespace="a"))
    s.create("pods", make_pod("p1", namespace="b"))
    assert len(s.list("pods")) == 2
    assert len(s.list("pods", namespace="a")) == 1


def test_update_conflict_detection():
    s = ClusterStore()
    created = s.create("nodes", make_node("n1"))
    rv = created["metadata"]["resourceVersion"]
    s.update("nodes", created, expect_rv=rv)
    with pytest.raises(ConflictError):
        s.update("nodes", created, expect_rv=rv)  # stale now


def test_patch_is_atomic_and_bumps_rv():
    s = ClusterStore()
    created = s.create("pods", make_pod("p1"))
    updated = s.patch(
        "pods", "p1", "default",
        lambda o: o["metadata"].setdefault("annotations", {}).update(x="y"),
    )
    assert updated["metadata"]["annotations"]["x"] == "y"
    assert updated["metadata"]["resourceVersion"] != created["metadata"]["resourceVersion"]


def test_watch_events():
    s = ClusterStore()
    w = s.watch(("pods",))
    s.create("pods", make_pod("p1"))
    s.create("nodes", make_node("n1"))  # not subscribed
    s.patch("pods", "p1", "default", lambda o: None)
    s.delete("pods", "p1", "default")
    events = [w.next(timeout=1) for _ in range(3)]
    assert [e.event_type for e in events] == [ADDED, MODIFIED, DELETED]
    assert all(e.kind == "pods" for e in events)
    assert w.next(timeout=0.05) is None
    w.close()


def test_update_defaults_namespace():
    s = ClusterStore()
    s.create("pods", make_pod("p1"))
    pod = {"metadata": {"name": "p1"}, "spec": {}}  # no namespace field
    s.update("pods", pod)
    listed = s.list("pods", namespace="default")
    assert len(listed) == 1 and listed[0]["metadata"]["namespace"] == "default"


def test_apply_unknown_kind_raises_not_found():
    s = ClusterStore()
    with pytest.raises(NotFoundError):
        s.apply("widgets", {"metadata": {"name": "w"}})


def test_dump_restore_reset_semantics():
    s = ClusterStore()
    s.create("nodes", make_node("n1"))
    initial = s.dump()
    s.create("nodes", make_node("n2"))
    s.delete("nodes", "n1")
    s.restore(initial)
    names = [n["metadata"]["name"] for n in s.list("nodes")]
    assert names == ["n1"]


def test_restore_keeps_resource_version_monotonic():
    s = ClusterStore()
    for i in range(5):
        s.create("nodes", make_node(f"n{i}"))
    dump = s.dump()
    fresh = ClusterStore()
    fresh.restore(dump)
    created = fresh.create("nodes", make_node("new"))
    restored_rvs = [int(n["metadata"]["resourceVersion"]) for n in fresh.list("nodes") if n["metadata"]["name"] != "new"]
    assert int(created["metadata"]["resourceVersion"]) > max(restored_rvs)


def test_watch_resume_replays_deletes_and_expires():
    from ksim_tpu.errors import ExpiredError
    from tests.helpers import make_pod

    store = ClusterStore()
    store.create("pods", make_pod("a"))
    b = store.create("pods", make_pod("b"))
    last = int(b["metadata"]["resourceVersion"])
    # Disconnect; a delete happens while away.
    store.delete("pods", "a")
    stream = store.watch(("pods",), since={"pods": last})
    ev = stream.next(timeout=1)
    assert ev is not None and ev.event_type == "DELETED"
    assert ev.obj["metadata"]["name"] == "a"
    # The DELETED event carries a fresh resourceVersion (> last).
    assert int(ev.obj["metadata"]["resourceVersion"]) > last
    stream.close()
    # A resume point older than the history buffer raises ExpiredError.
    store2 = ClusterStore()
    store2.HISTORY_DEPTH = 4
    store2._history = __import__("collections").deque(maxlen=4)
    for i in range(8):
        store2.create("pods", make_pod(f"p{i}"))
    try:
        store2.watch(("pods",), since={"pods": 1})
        raise AssertionError("expected ExpiredError")
    except ExpiredError:
        pass


def test_restore_emits_fresh_resource_versions():
    from tests.helpers import make_pod

    store = ClusterStore()
    store.create("pods", make_pod("a"))
    dump = store.dump()
    stream = store.watch(("pods",))
    store.restore(dump)
    rvs = []
    while True:
        ev = stream.next(timeout=0.2)
        if ev is None:
            break
        rvs.append(int(ev.obj["metadata"]["resourceVersion"]))
    stream.close()
    # DELETED then ADDED, both with fresh monotonically-increasing rvs.
    assert len(rvs) == 2 and rvs[0] < rvs[1] and rvs[0] > 1


def test_watch_resume_rejects_foreign_resume_points():
    """Resume points from a PREVIOUS store life answer Gone: a fresh
    store has no history to verify against, and a store whose history
    ends below the requested version never issued it.  Silently accepting
    either would leave the client's cache stale forever."""
    import pytest

    from ksim_tpu.errors import ExpiredError

    fresh = ClusterStore()
    with pytest.raises(ExpiredError):
        fresh.watch(("pods",), since={"pods": 5})

    store = ClusterStore()
    store.create("pods", make_pod("p1"))
    store.create("pods", make_pod("p2"))
    with pytest.raises(ExpiredError):
        store.watch(("pods",), since={"pods": 1000})  # ahead of history
    # A genuine resume point still replays the later event.
    first_rv = int(store.get("pods", "p1", "default")["metadata"]["resourceVersion"])
    stream = store.watch(("pods",), since={"pods": first_rv})
    ev = stream.next(timeout=1)
    assert ev is not None and ev.obj["metadata"]["name"] == "p2"
    stream.close()


def test_pod_node_name_partition_tracks_every_write_path():
    """The nodeName partition (pods_with_node / pods_without_node) must
    mirror the store through create, bind (patch), update, rewrap,
    delete, and restore — the scheduler reads one side instead of
    walking all pods every pass."""
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("a"))
    store.create("pods", make_pod("b", node_name="n1"))

    def names(side):
        return sorted(p["metadata"]["name"] for p in side)

    assert names(store.pods_without_node()) == ["a"]
    assert names(store.pods_with_node()) == ["b"]

    # Bind via patch: a moves sides.
    store.patch("pods", "a", "default", lambda o: o["spec"].__setitem__("nodeName", "n1"))
    assert names(store.pods_without_node()) == []
    assert names(store.pods_with_node()) == ["a", "b"]

    # Unbind via update (drain): b moves back.
    b = store.get("pods", "b", "default")
    b["spec"].pop("nodeName")
    store.update("pods", b)
    assert names(store.pods_without_node()) == ["b"]

    # Rewrap (the bind path's write primitive).
    store.rewrap(
        "pods", "b", "default",
        lambda cur: dict(
            cur,
            spec=dict(cur["spec"], nodeName="n1"),
            metadata=dict(cur["metadata"]),
        ),
    )
    assert names(store.pods_without_node()) == []

    # Delete drops the entry from its side.
    store.delete("pods", "a", "default")
    assert names(store.pods_with_node()) == ["b"]

    # Restore rebuilds the partition from the dump.
    dump = store.dump()
    store.create("pods", make_pod("c"))
    store.restore(dump)
    assert names(store.pods_with_node()) == ["b"]
    assert names(store.pods_without_node()) == []

    # Phase is deliberately NOT part of the partition: a Succeeded pod
    # with a nodeName stays on the with-node side (the requeue path must
    # still see it, matching the full-walk semantics).
    store.create("pods", make_pod("s", node_name="n1", phase="Succeeded"))
    assert "s" in names(store.pods_with_node())


def test_pods_without_node_is_name_sorted():
    """The without-node side is the scheduling queue's stable pre-order:
    it must come back (name, key)-sorted like list("pods")."""
    store = ClusterStore()
    for nm in ("zz", "aa", "mm"):
        store.create("pods", make_pod(nm))
    assert [p["metadata"]["name"] for p in store.pods_without_node()] == [
        "aa", "mm", "zz",
    ]


def test_restore_clears_node_bucket_index():
    """restore() must wipe the nodeName bucket index with the other pod
    partitions: a pre-reset bound pod must not appear in pods_on_nodes()
    after a restore that lacks it (review finding, round 5 — the stale
    entry fed a phantom pod into node-drain requeue, whose patch then
    raised NotFoundError)."""
    store = ClusterStore()
    boot = store.dump()
    store.create("pods", make_pod("ghost", node_name="n1"))
    assert len(store.pods_on_nodes(["n1"])) == 1
    store.restore(boot)
    assert store.pods_on_nodes(["n1"]) == []
    # And the index repopulates from a dump that HAS bound pods.
    store.create("pods", make_pod("real", node_name="n2"))
    snap = store.dump()
    store.restore(boot)
    store.restore(snap)
    assert [p["metadata"]["name"] for p in store.pods_on_nodes(["n2"])] == ["real"]


# ---------------------------------------------------------------------------
# Transactions (round 8: the atomic-segment-reconcile substrate)
# ---------------------------------------------------------------------------


def test_transaction_commit_delivers_events_in_write_order():
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    stream = store.watch(("pods", "nodes"))
    with store.transaction():
        store.create("pods", make_pod("p1"))
        store.patch(
            "pods", "p1", "default",
            lambda o: o["spec"].__setitem__("nodeName", "n1"),
        )
        store.delete("nodes", "n1")
        # Mid-transaction, the owning thread reads its own staged state...
        assert store.get("pods", "p1")["spec"]["nodeName"] == "n1"
        # ...but nothing has been delivered to watchers yet.
        assert stream.next(timeout=0) is None
    got = []
    while True:
        ev = stream.next(timeout=0)
        if ev is None:
            break
        got.append((ev.event_type, ev.kind, ev.obj["metadata"]["name"]))
    stream.close()
    assert got == [
        (ADDED, "pods", "p1"),
        (MODIFIED, "pods", "p1"),
        (DELETED, "nodes", "n1"),
    ]


def test_transaction_rollback_restores_objects_indexes_and_events():
    """An exception rolls every staged write back: objects, the sorted
    key order, the nodeName partition/bucket indexes — and no watch
    event is ever delivered (a watcher cannot observe the attempt)."""
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("keep"))
    store.create("pods", make_pod("bound", node_name="n1"))
    before_objs = store.dump()
    stream = store.watch(("pods", "nodes"))
    with pytest.raises(RuntimeError, match="boom"):
        with store.transaction():
            store.create("pods", make_pod("staged"))
            store.patch(
                "pods", "keep", "default",
                lambda o: o["spec"].__setitem__("nodeName", "n1"),
            )
            store.delete("pods", "bound", "default")
            store.delete("nodes", "n1")
            raise RuntimeError("boom")
    assert stream.next(timeout=0) is None  # nothing leaked
    stream.close()
    assert store.dump() == before_objs
    # Incremental indexes repaired, not just the object tables:
    assert [p["metadata"]["name"] for p in store.pods_without_node()] == ["keep"]
    assert [p["metadata"]["name"] for p in store.pods_on_nodes(["n1"])] == ["bound"]
    assert [n["metadata"]["name"] for n in store.list("nodes")] == ["n1"]
    # The store still works normally afterwards (watchers, indexes, rv).
    store.create("pods", make_pod("after"))
    assert store.get("pods", "after")["metadata"]["name"] == "after"


def test_transaction_rollback_restores_update_pre_image():
    store = ClusterStore()
    store.create("pods", make_pod("p1", cpu="100m"))
    rv_before = store.get("pods", "p1")["metadata"]["resourceVersion"]
    with pytest.raises(ValueError):
        with store.transaction():
            obj = store.get("pods", "p1")
            obj["metadata"]["labels"] = {"x": "1"}
            store.update("pods", obj)
            raise ValueError("abort")
    got = store.get("pods", "p1")
    assert got["metadata"].get("labels") == {}
    assert got["metadata"]["resourceVersion"] == rv_before


def test_transaction_nested_and_restore_refused():
    store = ClusterStore()
    with pytest.raises(RuntimeError, match="nested"):
        with store.transaction():
            with store.transaction():
                pass
    boot = store.dump()
    with pytest.raises(RuntimeError, match="restore"):
        with store.transaction():
            store.restore(boot)


def test_strict_mode_asserts_lock_held_on_internal_mutators():
    """Sanitizer-lite (KSIM_STORE_STRICT / strict=True, docs/lint.md):
    internal mutators called without the store lock raise, with it (and
    through every public API path) they work exactly as before."""
    from ksim_tpu.state.cluster import ADDED, WatchEvent

    store = ClusterStore(strict=True)
    # Public API acquires the lock itself: unchanged behavior.
    store.create("pods", make_pod("ok"))
    store.patch("pods", "ok", "default", lambda o: o["metadata"].setdefault(
        "labels", {}
    ).update(x="y"))
    store.delete("pods", "ok", "default")
    with store.transaction():
        store.create("pods", make_pod("txn"))
    # Internal mutators without the lock: loud AssertionError.
    ev = WatchEvent("pods", ADDED, make_pod("raw"))
    with pytest.raises(AssertionError, match="KSIM_STORE_STRICT"):
        store._notify(ev)
    with pytest.raises(AssertionError, match="KSIM_STORE_STRICT"):
        store._index_pod("default/raw", None)
    with pytest.raises(AssertionError, match="KSIM_STORE_STRICT"):
        store._touch("pods", "default/raw")
    # Under the lock the same calls are legal (the lock-held contract).
    with store._lock:
        store._notify(ev)


def test_strict_mode_default_comes_from_env(monkeypatch):
    monkeypatch.setenv("KSIM_STORE_STRICT", "1")
    assert ClusterStore()._strict
    monkeypatch.delenv("KSIM_STORE_STRICT")
    assert not ClusterStore()._strict
    # Explicit argument beats the environment either way.
    monkeypatch.setenv("KSIM_STORE_STRICT", "1")
    assert not ClusterStore(strict=False)._strict
