"""SchedulerService: bind + annotate loop over the ClusterStore."""

import json
import time

from ksim_tpu.engine.annotations import (
    RESULT_HISTORY_KEY,
    SELECTED_NODE_KEY,
)
from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from tests.helpers import make_node, make_pod


def make_store(nodes=(), pods=()):
    store = ClusterStore()
    for n in nodes:
        store.create("nodes", n)
    for p in pods:
        store.create("pods", p)
    return store


def test_schedule_pending_binds_and_annotates():
    store = make_store([make_node("n1"), make_node("n2")], [make_pod("p1"), make_pod("p2")])
    svc = SchedulerService(store)
    placements = svc.schedule_pending()
    assert set(placements) == {"default/p1", "default/p2"}
    for key, node in placements.items():
        assert node in ("n1", "n2")
    p1 = store.get("pods", "p1", "default")
    assert p1["spec"]["nodeName"] == placements["default/p1"]
    assert p1["status"]["phase"] == "Running"
    annos = p1["metadata"]["annotations"]
    assert annos[SELECTED_NODE_KEY] == placements["default/p1"]
    assert len(json.loads(annos[RESULT_HISTORY_KEY])) == 1


def test_priority_order_wins_contended_capacity():
    # One slot; the high-priority pod (created later) must get it.
    store = make_store(
        [make_node("n1", cpu="1", memory="1Gi")],
        [
            make_pod("low", cpu="800m", priority=1),
            make_pod("high", cpu="800m", priority=100),
        ],
    )
    placements = SchedulerService(store).schedule_pending()
    assert placements["default/high"] == "n1"
    assert placements["default/low"] is None
    low = store.get("pods", "low", "default")
    assert "nodeName" not in low["spec"]
    # Unschedulable attempt still recorded.
    assert RESULT_HISTORY_KEY in low["metadata"]["annotations"]


def test_retry_history_accumulates():
    store = make_store([make_node("tiny", cpu="100m")], [make_pod("big", cpu="2")])
    svc = SchedulerService(store, preemption=False)
    assert svc.schedule_pending()["default/big"] is None
    # The unschedulable pod is in backoff; a cluster event flushes it and
    # the retry appends to the result history.
    svc.flush_backoff()
    assert svc.schedule_pending()["default/big"] is None
    annos = store.get("pods", "big", "default")["metadata"]["annotations"]
    assert len(json.loads(annos[RESULT_HISTORY_KEY])) == 2


def test_foreign_scheduler_name_ignored():
    pod = make_pod("other")
    pod["spec"]["schedulerName"] = "my-custom-scheduler"
    store = make_store([make_node("n1")], [pod])
    assert SchedulerService(store).schedule_pending() == {}


def test_watch_loop_schedules_new_pods_and_reacts_to_new_nodes():
    store = make_store([make_node("tiny", cpu="100m")])
    svc = SchedulerService(store).start()
    try:
        store.create("pods", make_pod("big", cpu="2"))
        deadline = time.time() + 30
        while time.time() < deadline:
            annos = store.get("pods", "big", "default")["metadata"].get("annotations", {})
            if RESULT_HISTORY_KEY in annos:
                break
            time.sleep(0.05)
        assert RESULT_HISTORY_KEY in annos  # attempted, unschedulable
        assert "nodeName" not in store.get("pods", "big", "default")["spec"]
        # Capacity arrives: the loop reschedules and binds.
        store.create("nodes", make_node("roomy", cpu="8"))
        deadline = time.time() + 30
        while time.time() < deadline:
            pod = store.get("pods", "big", "default")
            if pod["spec"].get("nodeName"):
                break
            time.sleep(0.05)
        assert pod["spec"].get("nodeName") == "roomy"
    finally:
        svc.stop()


def test_unschedulable_backoff_skips_and_flushes():
    """Upstream backoff-queue analogue: an unschedulable pod skips
    passes exponentially; capacity-freed/topology events flush it."""
    from tests.helpers import make_node, make_pod

    store = ClusterStore()
    store.create("nodes", make_node("n0", cpu="1", memory="8Gi"))
    store.create("pods", make_pod("big", cpu="2", memory=None))
    svc = SchedulerService(store, preemption=False)
    assert svc.schedule_pending() == {"default/big": None}  # attempt 1
    # Backoff: the next pass skips it entirely.
    assert svc.schedule_pending() == {}
    # A node event flushes the backoff and it schedules.
    store.create("nodes", make_node("n1", cpu="4", memory="8Gi"))
    svc.flush_backoff()
    assert svc.schedule_pending() == {"default/big": "n1"}
    # Scheduling cleared the backoff entry.
    assert svc._backoff == {}


def test_multiple_profiles_schedule_their_own_pods():
    """Two profiles in one config: each schedules only pods addressed to
    its schedulerName, sequentially sharing cluster capacity."""
    from tests.helpers import make_node, make_pod

    store = ClusterStore()
    store.create("nodes", make_node("n0", cpu="2", memory="8Gi"))
    a = make_pod("a", cpu="1", memory=None)
    b = make_pod("b", cpu="1", memory=None)
    b["spec"]["schedulerName"] = "second"
    c = make_pod("c", cpu="1", memory=None)
    c["spec"]["schedulerName"] = "unknown-scheduler"
    for p in (a, b, c):
        store.create("pods", p)
    svc = SchedulerService(store, config={
        "profiles": [
            {"schedulerName": "default-scheduler"},
            {"schedulerName": "second"},
        ]
    })
    placements = svc.schedule_pending()
    # Both profiles' pods bound; the unknown scheduler's pod untouched.
    assert placements == {"default/a": "n0", "default/b": "n0"}
    assert store.get("pods", "c")["spec"].get("nodeName") is None
    # Capacity was shared: 2 cpu total, both 1-cpu pods fit exactly.
    assert store.get("pods", "a")["spec"]["nodeName"] == "n0"
    assert store.get("pods", "b")["spec"]["nodeName"] == "n0"
