"""More independently-derived upstream-v1.30 fixtures: NodeAffinity
scoring and the volume family filters.

Like tests/test_upstream_fixtures.py, every expected value below is
hand-computed from the upstream algorithm definitions (cited per test) —
never from the repo's oracle — and asserted against BOTH the oracle and
the compiled kernels through the engine.
"""

from __future__ import annotations

import pytest

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins import oracle
from ksim_tpu.state.featurizer import Featurizer
from tests.fixtures import upstream_v130 as fx
from tests.helpers import make_node, make_pod, pods_by_node

ZONE_KEY = "topology.kubernetes.io/zone"


def _engine_result(nodes, bound, queue, **volume_kw):
    feats = Featurizer().featurize(nodes, bound, queue_pods=queue, **volume_kw)
    eng = Engine(feats, default_plugins(feats), record="full")
    return feats, eng.evaluate_batch()


def test_node_affinity_preferred_scoring_fixture():
    """node_affinity.go Score = sum of matched preferred-term weights;
    NormalizeScore = DefaultNormalizeScore(100, reverse=false):
      raw = [80+20, 80, 0] = [100, 80, 0]; max = 100
      normalized = [100*100/100, 100*80/100, 0] = [100, 80, 0]
    (weights sum BEFORE normalization; the 0-weight term never counts).
    """
    nodes = [
        make_node("both", labels={"disk": "ssd", "gpu": "yes"}),
        make_node("ssd-only", labels={"disk": "ssd"}),
        make_node("neither", labels={"disk": "hdd"}),
    ]
    pod = make_pod("p0")
    pod["spec"]["affinity"] = {
        "nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {
                    "weight": 80,
                    "preference": {
                        "matchExpressions": [
                            {"key": "disk", "operator": "In", "values": ["ssd"]}
                        ]
                    },
                },
                {
                    "weight": 20,
                    "preference": {
                        "matchExpressions": [
                            {"key": "gpu", "operator": "Exists"}
                        ]
                    },
                },
            ]
        }
    }
    infos = oracle.build_node_infos(nodes, [])
    raw = [oracle.node_affinity_score(pod, info) for info in infos]
    assert raw == [100, 80, 0]
    assert oracle.default_normalize_score(raw, reverse=False) == [100, 80, 0]

    _feats, res = _engine_result(nodes, [], [pod])
    si = res.plugin_names.index("NodeAffinity")
    weight = 2  # upstream default-profile weight
    assert [int(res.scores[0, si, ni]) for ni in range(3)] == [100, 80, 0]
    assert [int(res.final_scores[0, si, ni]) for ni in range(3)] == [
        weight * s for s in (100, 80, 0)
    ]


def _pvc(name, volume_name="", storage_class="", access_modes=("ReadWriteOnce",)):
    spec = {"accessModes": list(access_modes)}
    if volume_name:
        spec["volumeName"] = volume_name
    if storage_class:
        spec["storageClassName"] = storage_class
    return {
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
        "status": {"phase": "Bound" if volume_name else "Pending"},
    }


def _pv(name, *, zone=None, node_affinity_host=None, phase="Available"):
    pv = {
        "metadata": {"name": name, "labels": {}},
        "spec": {"capacity": {"storage": "1Gi"}, "accessModes": ["ReadWriteOnce"]},
        "status": {"phase": phase},
    }
    if zone:
        pv["metadata"]["labels"][ZONE_KEY] = zone
    if node_affinity_host:
        pv["spec"]["nodeAffinity"] = {
            "required": {
                "nodeSelectorTerms": [
                    {
                        "matchExpressions": [
                            {
                                "key": "kubernetes.io/hostname",
                                "operator": "In",
                                "values": [node_affinity_host],
                            }
                        ]
                    }
                ]
            }
        }
    return pv


def _pod_with_pvc(name, claim):
    pod = make_pod(name)
    pod["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": claim}}
    ]
    return pod


def test_volume_zone_filter_fixture():
    """volume_zone.go: a pod using a PVC bound to a PV labeled with a
    zone may only land on nodes whose zone label matches (exact upstream
    semantics: the node must carry the PV's zone value)."""
    nodes = [
        make_node("in-zone", labels={ZONE_KEY: "z1", "kubernetes.io/hostname": "in-zone"}),
        make_node("out-zone", labels={ZONE_KEY: "z2", "kubernetes.io/hostname": "out-zone"}),
    ]
    pvs = [_pv("pv-z1", zone="z1", phase="Bound")]
    pvs[0]["spec"]["claimRef"] = {"name": "claim-a", "namespace": "default"}
    pvcs = [_pvc("claim-a", volume_name="pv-z1")]
    pod = _pod_with_pvc("p0", "claim-a")

    for node, want_pass in ((nodes[0], True), (nodes[1], False)):
        reasons = oracle.volume_zone_filter(pod, node, pvcs, pvs)
        assert (not reasons) == want_pass, node["metadata"]["name"]

    _feats, res = _engine_result(
        nodes, [], [pod], pvs=pvs, pvcs=pvcs, storage_classes=[]
    )
    fi = res.filter_plugin_names.index("VolumeZone")
    assert int(res.reason_bits[0, fi, 0]) == 0
    assert int(res.reason_bits[0, fi, 1]) != 0


def test_volume_binding_node_affinity_fixture():
    """volume_binding.go: a bound PV's nodeAffinity restricts the pod to
    admitted nodes ("node(s) had volume node affinity conflict")."""
    nodes = [
        make_node("node-a", labels={"kubernetes.io/hostname": "node-a"}),
        make_node("node-b", labels={"kubernetes.io/hostname": "node-b"}),
    ]
    pvs = [_pv("pv-a", node_affinity_host="node-a", phase="Bound")]
    pvs[0]["spec"]["claimRef"] = {"name": "claim-a", "namespace": "default"}
    pvcs = [_pvc("claim-a", volume_name="pv-a")]
    pod = _pod_with_pvc("p0", "claim-a")

    for node, want_pass in ((nodes[0], True), (nodes[1], False)):
        reasons = oracle.volume_binding_filter(pod, node, pvcs, pvs, [])
        assert (not reasons) == want_pass, node["metadata"]["name"]

    _feats, res = _engine_result(
        nodes, [], [pod], pvs=pvs, pvcs=pvcs, storage_classes=[]
    )
    fi = res.filter_plugin_names.index("VolumeBinding")
    assert int(res.reason_bits[0, fi, 0]) == 0
    assert int(res.reason_bits[0, fi, 1]) != 0


def test_volume_binding_unbound_claims_fixture():
    """volume_binding.go unbound-PVC semantics:
    - an unbound PVC whose StorageClass is Immediate -> unschedulable
      everywhere ("pod has unbound immediate PersistentVolumeClaims");
    - WaitForFirstConsumer with a dynamically-provisionable class -> every
      node passes (provisioning satisfies it);
    - a missing PVC -> unschedulable everywhere."""
    nodes = [make_node("n0"), make_node("n1")]
    scs = [
        {
            "metadata": {"name": "immediate-sc"},
            "provisioner": "ebs.csi.aws.com",
            "volumeBindingMode": "Immediate",
        },
        {
            "metadata": {"name": "wffc-sc"},
            "provisioner": "ebs.csi.aws.com",
            "volumeBindingMode": "WaitForFirstConsumer",
        },
    ]
    cases = [
        (_pvc("imm-claim", storage_class="immediate-sc"), "imm-claim", False),
        (_pvc("wffc-claim", storage_class="wffc-sc"), "wffc-claim", True),
        (None, "ghost-claim", False),
    ]
    for pvc, claim, want_pass in cases:
        pvcs = [pvc] if pvc else []
        pod = _pod_with_pvc("p0", claim)
        for node in nodes:
            reasons = oracle.volume_binding_filter(pod, node, pvcs, [], scs)
            assert (not reasons) == want_pass, (claim, node["metadata"]["name"])
        _feats, res = _engine_result(
            nodes, [], [pod], pvs=[], pvcs=pvcs, storage_classes=scs
        )
        fi = res.filter_plugin_names.index("VolumeBinding")
        for ni in range(2):
            passes = int(res.reason_bits[0, fi, ni]) == 0
            assert passes == want_pass, (claim, ni)


def test_node_ports_conflict_fixture():
    """nodeports/node_ports.go Fits: a (hostIP, protocol, hostPort)
    triple conflicts with an existing pod's triple iff the ports and
    protocols match and either side binds 0.0.0.0 (or the IPs match)."""
    nodes = [make_node("node-a"), make_node("node-b")]
    holder = make_pod("holder", node_name="node-a")
    holder["spec"]["containers"][0]["ports"] = [
        {"hostPort": 8080, "protocol": "TCP"}  # hostIP defaults 0.0.0.0
    ]
    cases = [
        # Same port+protocol vs a 0.0.0.0 binder -> conflict even with a
        # specific hostIP.
        ({"hostPort": 8080, "protocol": "TCP", "hostIP": "10.0.0.1"}, False),
        # Different port -> fits.
        ({"hostPort": 8081, "protocol": "TCP"}, True),
        # Different protocol -> fits.
        ({"hostPort": 8080, "protocol": "UDP"}, True),
    ]
    for port, fits_a in cases:
        pod = make_pod("incoming")
        pod["spec"]["containers"][0]["ports"] = [dict(port)]
        want = [] if fits_a else ["node(s) didn't have free ports for the requested pod ports"]
        got = oracle.node_ports_filter(pod, [holder])
        assert (not got) == (not want), (port, got)
        _feats, res = _engine_result(nodes, [holder], [pod])
        fi = res.filter_plugin_names.index("NodePorts")
        assert (int(res.reason_bits[0, fi, 0]) == 0) == fits_a, port
        assert int(res.reason_bits[0, fi, 1]) == 0, port  # node-b always free


def test_node_unschedulable_and_toleration_fixture():
    """node_unschedulable.go: spec.unschedulable fails the filter unless
    the pod tolerates node.kubernetes.io/unschedulable:NoSchedule."""
    nodes = [make_node("open"), make_node("cordoned", unschedulable=True)]
    plain = make_pod("plain")
    tolerant = make_pod(
        "tolerant",
        tolerations=[
            {"key": "node.kubernetes.io/unschedulable", "effect": "NoSchedule"}
        ],
    )
    infos = oracle.build_node_infos(nodes, [])
    assert oracle.node_unschedulable_filter(plain, infos[1])  # blocked
    assert not oracle.node_unschedulable_filter(plain, infos[0])
    assert not oracle.node_unschedulable_filter(tolerant, infos[1])  # tolerated

    _feats, res = _engine_result(nodes, [], [plain, tolerant])
    fi = res.filter_plugin_names.index("NodeUnschedulable")
    assert int(res.reason_bits[0, fi, 0]) == 0
    assert int(res.reason_bits[0, fi, 1]) != 0  # plain blocked on cordoned
    assert int(res.reason_bits[1, fi, 1]) == 0  # tolerant passes


def test_taint_toleration_filter_fixture():
    """taint_toleration.go Filter: the first untolerated NoSchedule/
    NoExecute taint rejects; PreferNoSchedule never filters."""
    nodes = [
        make_node("clean"),
        make_node("tainted", taints=[{"key": "k", "value": "v", "effect": "NoSchedule"}]),
        make_node("soft", taints=[{"key": "k", "value": "v", "effect": "PreferNoSchedule"}]),
    ]
    plain = make_pod("plain")
    tolerant = make_pod(
        "tolerant",
        tolerations=[{"key": "k", "operator": "Equal", "value": "v", "effect": "NoSchedule"}],
    )
    _feats, res = _engine_result(nodes, [], [plain, tolerant])
    fi = res.filter_plugin_names.index("TaintToleration")
    # plain: clean ok, NoSchedule blocked, PreferNoSchedule ok (score-only).
    assert int(res.reason_bits[0, fi, 0]) == 0
    assert int(res.reason_bits[0, fi, 1]) != 0
    assert int(res.reason_bits[0, fi, 2]) == 0
    # tolerant passes everywhere.
    for ni in range(3):
        assert int(res.reason_bits[1, fi, ni]) == 0


def test_node_name_filter_fixture():
    """nodename/node_name.go: spec.nodeName pins the pod to that node;
    an unset/empty nodeName passes everywhere."""
    nodes = [make_node("wanted"), make_node("other")]
    unset = make_pod("unset")
    unset["spec"]["nodeName"] = ""
    pinned = make_pod("really-pinned")
    pinned["spec"]["nodeName"] = "wanted"
    _feats, res = _engine_result(nodes, [], [unset, pinned])
    fi = res.filter_plugin_names.index("NodeName")
    for ni in range(2):  # unset passes everywhere
        assert int(res.reason_bits[0, fi, ni]) == 0
    assert int(res.reason_bits[1, fi, 0]) == 0  # wanted passes
    assert int(res.reason_bits[1, fi, 1]) != 0  # other blocked
    infos = oracle.build_node_infos(nodes, [])
    assert not oracle.node_name_filter(unset, infos[0])
    assert not oracle.node_name_filter(unset, infos[1])
    assert not oracle.node_name_filter(pinned, infos[0])
    assert oracle.node_name_filter(pinned, infos[1])


def test_node_affinity_required_operators_fixture():
    """nodeaffinity.go required terms with the full operator set:
    Gt/Lt compare integer label values, NotIn rejects listed values (and
    passes when the key is absent), matchFields matches metadata.name
    (upstream supports only that field)."""
    nodes = [
        make_node("big", labels={"cpu-gen": "9"}),
        make_node("small", labels={"cpu-gen": "3"}),
        make_node("unlabeled"),
    ]

    def pod_with_term(term):
        pod = make_pod("p")
        pod["spec"]["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [term]
                }
            }
        }
        return pod

    cases = [
        # Gt 5: only big (9 > 5); unlabeled has no value -> fail.
        ({"matchExpressions": [{"key": "cpu-gen", "operator": "Gt", "values": ["5"]}]},
         [True, False, False]),
        # Lt 5: only small.
        ({"matchExpressions": [{"key": "cpu-gen", "operator": "Lt", "values": ["5"]}]},
         [False, True, False]),
        # NotIn ["9"]: small passes, big fails, ABSENT key passes
        # (upstream NotIn matches when the label is missing).
        ({"matchExpressions": [{"key": "cpu-gen", "operator": "NotIn", "values": ["9"]}]},
         [False, True, True]),
        # DoesNotExist: only unlabeled.
        ({"matchExpressions": [{"key": "cpu-gen", "operator": "DoesNotExist"}]},
         [False, False, True]),
        # matchFields on metadata.name.
        ({"matchFields": [{"key": "metadata.name", "operator": "In", "values": ["small"]}]},
         [False, True, False]),
    ]
    infos = oracle.build_node_infos(nodes, [])
    for term, want in cases:
        pod = pod_with_term(term)
        got_oracle = [not oracle.node_affinity_filter(pod, info) for info in infos]
        assert got_oracle == want, (term, got_oracle)
        _feats, res = _engine_result(nodes, [], [pod])
        fi = res.filter_plugin_names.index("NodeAffinity")
        got_kernel = [int(res.reason_bits[0, fi, ni]) == 0 for ni in range(3)]
        assert got_kernel == want, (term, got_kernel)


def test_topology_spread_min_domains_fixture():
    """filtering.go minDomains (stable since v1.27): when the number of
    eligible domains is BELOW minDomains, the global minimum match count
    is treated as 0.  Layout: z1 and z2 each hold ONE matching pod; the
    incoming pod matches its own selector (selfMatchNum = 1).

    - without minDomains: min = 1 -> skew = 1+1-1 = 1 <= maxSkew -> both
      zones schedulable;
    - minDomains=3 (> 2 domains): min treated as 0 -> skew = 1+1-0 = 2 >
      maxSkew -> BOTH zones violate."""
    nodes = [
        make_node("za-node", labels={ZONE_KEY: "za", "kubernetes.io/hostname": "za-node"}),
        make_node("zb-node", labels={ZONE_KEY: "zb", "kubernetes.io/hostname": "zb-node"}),
    ]
    bound = [
        make_pod("e-a", labels={"app": "spread"}, node_name="za-node"),
        make_pod("e-b", labels={"app": "spread"}, node_name="zb-node"),
    ]

    def incoming(min_domains):
        con = {
            "maxSkew": 1,
            "topologyKey": ZONE_KEY,
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "spread"}},
        }
        if min_domains is not None:
            con["minDomains"] = min_domains
        return make_pod(
            "incoming", labels={"app": "spread"}, topology_spread_constraints=[con]
        )

    from tests.helpers import pods_by_node

    infos = oracle.build_node_infos(nodes, bound)
    for min_domains, want_pass in ((None, True), (3, False)):
        pod = incoming(min_domains)
        rows = oracle.topology_spread_filter_all(pod, infos, pods_by_node(bound))
        assert all((not r) == want_pass for r in rows), (min_domains, rows)
        _feats, res = _engine_result(nodes, bound, [pod])
        fi = res.filter_plugin_names.index("PodTopologySpread")
        for ni in range(2):
            assert (int(res.reason_bits[0, fi, ni]) == 0) == want_pass, (
                min_domains, ni,
            )


def test_image_locality_duplicate_container_images_fixture():
    """image_locality.go sumImageScores iterates CONTAINERS, so two
    containers sharing one image count its scaled score twice:
      1 node total -> scaled = size * 1/1 = 300 MB; sum = 600 MB
      maxThreshold = 1000 MB * 2 containers = 2000 MB
      score = int(100 * (600-23) / (2000-23)) = int(29.18) = 29."""
    node = make_node("n0")
    node["status"]["images"] = [{"names": ["img-shared"], "sizeBytes": 300 * 1024 * 1024}]
    pod = make_pod("p0")
    pod["spec"]["containers"] = [
        {"name": "c1", "image": "img-shared", "resources": {"requests": {"cpu": "100m"}}},
        {"name": "c2", "image": "img-shared", "resources": {"requests": {"cpu": "100m"}}},
    ]
    states = oracle.build_image_states([node])
    assert oracle.image_locality_score(pod, node, states, 1) == 29
    _feats, res = _engine_result([node], [], [pod])
    si = res.plugin_names.index("ImageLocality")
    assert int(res.scores[0, si, 0]) == 29


def test_fit_too_many_pods_fixture():
    """fit.go fitsRequest checks pod COUNT capacity first: a node whose
    `pods` allocatable is exhausted reports exactly "Too many pods" even
    when cpu/memory fit."""
    nodes = [make_node("full", pods=1), make_node("free", pods=10)]
    bound = [make_pod("occupier", node_name="full")]
    pod = make_pod("incoming", cpu="100m", memory="64Mi")
    infos = oracle.build_node_infos(nodes, bound)
    assert oracle.fit_filter(pod, infos[0]) == ["Too many pods"]
    assert oracle.fit_filter(pod, infos[1]) == []
    _feats, res = _engine_result(nodes, bound, [pod])
    fi = res.filter_plugin_names.index("NodeResourcesFit")
    from ksim_tpu.plugins.noderesources import NodeResourcesFit

    fit = NodeResourcesFit(_feats.resources)
    assert fit.decode_reasons(int(res.reason_bits[0, fi, 0])) == ["Too many pods"]
    assert int(res.reason_bits[0, fi, 1]) == 0


from tests.test_upstream_fixtures import _ipa_term


def _zone_cluster():
    zones = {"node-a": "z1", "node-b": "z1", "node-x": "z2", "node-y": "z2"}
    return [
        make_node(n, labels={ZONE_KEY: z, "kubernetes.io/hostname": n})
        for n, z in zones.items()
    ]


def _ipa_norm(nodes, bound, pod):
    from tests.helpers import pods_by_node

    infos = oracle.build_node_infos(nodes, bound)
    raw, norm = oracle.inter_pod_affinity_score_all(
        pod, infos, pods_by_node(bound), [True] * len(infos)
    )
    _feats, res = _engine_result(nodes, bound, [pod])
    si = res.plugin_names.index("InterPodAffinity")
    plugin_weight = 2  # upstream default-profile weight
    kernel_norm = [int(res.final_scores[0, si, ni]) // plugin_weight for ni in range(len(infos))]
    return [i["name"] for i in infos], raw, norm, kernel_norm


def test_interpod_preferred_anti_affinity_subtracts_fixture():
    """scoring.go: the incoming pod's preferred ANTI-affinity terms
    SUBTRACT their weight for every matching existing pod in the domain:
      raw = [-10, -10, 0, 0]; min -10, max 0
      normalized = 100 * (raw - min) / (max - min) = [0, 0, 100, 100]."""
    nodes = _zone_cluster()
    bound = [make_pod("db0", labels={"app": "db"}, node_name="node-a")]
    pod = make_pod("incoming")
    pod["spec"]["affinity"] = {
        "podAntiAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term(ZONE_KEY, {"app": "db"}, weight=10)
            ]
        }
    }
    names, raw, norm, kernel_norm = _ipa_norm(nodes, bound, pod)
    want_raw = {"node-a": -10, "node-b": -10, "node-x": 0, "node-y": 0}
    want_norm = {"node-a": 0, "node-b": 0, "node-x": 100, "node-y": 100}
    assert dict(zip(names, raw)) == want_raw
    assert dict(zip(names, norm)) == want_norm
    assert dict(zip(names, kernel_norm)) == want_norm


def test_interpod_existing_preferred_affinity_symmetric_fixture():
    """scoring.go symmetry: an EXISTING pod's preferred affinity term
    matching the incoming pod adds its weight to the existing pod's
    domain, even when the incoming pod declares no affinity at all:
      raw = [7, 7, 0, 0] -> normalized [100, 100, 0, 0]."""
    nodes = _zone_cluster()
    holder = make_pod("holder", node_name="node-a")
    holder["spec"]["affinity"] = {
        "podAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term(ZONE_KEY, {"team": "blue"}, weight=7)
            ]
        }
    }
    pod = make_pod("incoming", labels={"team": "blue"})
    names, raw, norm, kernel_norm = _ipa_norm(nodes, [holder], pod)
    want_norm = {"node-a": 100, "node-b": 100, "node-x": 0, "node-y": 0}
    assert dict(zip(names, raw)) == {"node-a": 7, "node-b": 7, "node-x": 0, "node-y": 0}
    assert dict(zip(names, norm)) == want_norm
    assert dict(zip(names, kernel_norm)) == want_norm


def test_interpod_existing_preferred_anti_symmetric_fixture():
    """scoring.go symmetry, anti direction: an EXISTING pod's preferred
    anti-affinity term matching the incoming pod subtracts on the
    existing pod's domain (hostname here, so only node-a):
      raw = [-4, 0, 0, 0] -> normalized [0, 100, 100, 100]."""
    nodes = _zone_cluster()
    holder = make_pod("holder", node_name="node-a")
    holder["spec"]["affinity"] = {
        "podAntiAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term("kubernetes.io/hostname", {"team": "red"}, weight=4)
            ]
        }
    }
    pod = make_pod("incoming", labels={"team": "red"})
    names, raw, norm, kernel_norm = _ipa_norm(nodes, [holder], pod)
    want_norm = {"node-a": 0, "node-b": 100, "node-x": 100, "node-y": 100}
    assert dict(zip(names, raw)) == {"node-a": -4, "node-b": 0, "node-x": 0, "node-y": 0}
    assert dict(zip(names, norm)) == want_norm
    assert dict(zip(names, kernel_norm)) == want_norm


def test_bare_exists_toleration_tolerates_everything_fixture():
    """v1.Toleration.ToleratesTaint: operator Exists with an EMPTY key
    tolerates every taint (and an empty effect matches all effects)."""
    nodes = [
        make_node("hostile", taints=[
            {"key": "a", "value": "1", "effect": "NoSchedule"},
            {"key": "b", "value": "2", "effect": "NoExecute"},
        ]),
    ]
    pod = make_pod("tolerates-all", tolerations=[{"operator": "Exists"}])
    blocked = make_pod("blocked")
    infos = oracle.build_node_infos(nodes, [])
    assert not oracle.taint_toleration_filter(pod, infos[0])
    assert oracle.taint_toleration_filter(blocked, infos[0])
    _feats, res = _engine_result(nodes, [], [pod, blocked])
    fi = res.filter_plugin_names.index("TaintToleration")
    assert int(res.reason_bits[0, fi, 0]) == 0
    assert int(res.reason_bits[1, fi, 0]) != 0


def test_node_volume_limits_fixture():
    """nodevolumelimits (CSI): a node advertising
    attachable-volumes-csi-<driver> admits at most that many attachments
    of the driver's volumes; a bound pod's attachment counts against the
    limit, and a pod reusing an ALREADY-ATTACHED volume does not add one."""
    node_full = make_node(
        "limit-1", extra_alloc={"attachable-volumes-csi-ebs.csi.aws.com": "1"}
    )
    node_free = make_node(
        "limit-2", extra_alloc={"attachable-volumes-csi-ebs.csi.aws.com": "2"}
    )
    nodes = [node_full, node_free]

    def csi_pv(name):
        return {
            "metadata": {"name": name},
            "spec": {
                "capacity": {"storage": "1Gi"},
                "accessModes": ["ReadWriteMany"],
                "csi": {"driver": "ebs.csi.aws.com", "volumeHandle": name},
                "claimRef": {"name": f"{name}-claim", "namespace": "default"},
            },
            "status": {"phase": "Bound"},
        }

    pvs = [csi_pv("pv-1"), csi_pv("pv-2")]
    pvcs = [
        _pvc("pv-1-claim", volume_name="pv-1", access_modes=("ReadWriteMany",)),
        _pvc("pv-2-claim", volume_name="pv-2", access_modes=("ReadWriteMany",)),
    ]
    holder = _pod_with_pvc("holder", "pv-1-claim")
    holder["spec"]["nodeName"] = "limit-1"

    # A NEW volume on the full node exceeds the limit of 1.
    newvol = _pod_with_pvc("newvol", "pv-2-claim")
    reasons_full = oracle.node_volume_limits_filter(
        newvol, node_full, [holder], pvcs, pvs, []
    )
    reasons_free = oracle.node_volume_limits_filter(
        newvol, node_free, [], pvcs, pvs, []
    )
    assert reasons_full == ["node(s) exceed max volume count"]
    assert reasons_free == []
    # Re-using the ALREADY-ATTACHED pv-1 adds no attachment: fits.
    sharer = _pod_with_pvc("sharer", "pv-1-claim")
    assert oracle.node_volume_limits_filter(
        sharer, node_full, [holder], pvcs, pvs, []
    ) == []

    _feats, res = _engine_result(
        nodes, [holder], [newvol, sharer], pvs=pvs, pvcs=pvcs, storage_classes=[]
    )
    fi = res.filter_plugin_names.index("NodeVolumeLimits")
    assert int(res.reason_bits[0, fi, 0]) != 0  # newvol blocked on limit-1
    assert int(res.reason_bits[0, fi, 1]) == 0  # fits limit-2
    assert int(res.reason_bits[1, fi, 0]) == 0  # sharer fits limit-1


def test_node_selector_ands_with_required_affinity_fixture():
    """nodeaffinity.go GetRequiredNodeAffinity: plain spec.nodeSelector
    and affinity.required are BOTH required (AND); the required terms
    themselves OR together."""
    nodes = [
        make_node("both", labels={"pool": "p1", "disk": "ssd"}),
        make_node("selector-only", labels={"pool": "p1", "disk": "hdd"}),
        make_node("affinity-only", labels={"pool": "p2", "disk": "ssd"}),
    ]
    pod = make_pod("strict", node_selector={"pool": "p1"})
    pod["spec"]["affinity"] = {
        "nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {"matchExpressions": [
                        {"key": "disk", "operator": "In", "values": ["ssd"]}]},
                    {"matchExpressions": [
                        {"key": "disk", "operator": "In", "values": ["nvme"]}]},
                ]
            }
        }
    }
    want = {"both": True, "selector-only": False, "affinity-only": False}
    infos = oracle.build_node_infos(nodes, [])
    for info in infos:
        got = not oracle.node_affinity_filter(pod, info)
        assert got == want[info["name"]], info["name"]
    _feats, res = _engine_result(nodes, [], [pod])
    fi = res.filter_plugin_names.index("NodeAffinity")
    for ni, info in enumerate(infos):
        got = int(res.reason_bits[0, fi, ni]) == 0
        assert got == want[info["name"]], info["name"]


def test_image_name_normalization_fixture():
    """imagelocality normalizedImageName: a tag-less reference equals its
    :latest form (and a digest/tag suffix is left alone), so a pod asking
    for "img" scores against a node advertising "img:latest"."""
    node = make_node("n0")
    node["status"]["images"] = [
        {"names": ["registry.example/app:latest"], "sizeBytes": 500 * 1024 * 1024}
    ]
    pod = make_pod("p0")
    pod["spec"]["containers"] = [
        {"name": "c", "image": "registry.example/app",
         "resources": {"requests": {"cpu": "100m"}}}
    ]
    # 1 node: scaled = 500MB; score = int(100 * (500-23)/(1000-23)) = 48
    states = oracle.build_image_states([node])
    assert oracle.image_locality_score(pod, node, states, 1) == 48
    _feats, res = _engine_result([node], [], [pod])
    si = res.plugin_names.index("ImageLocality")
    assert int(res.scores[0, si, 0]) == 48


def test_match_labels_and_expressions_combined_fixture():
    """metav1.LabelSelector: matchLabels and matchExpressions AND
    together (used verbatim by topology spread / inter-pod selectors)."""
    from ksim_tpu.state.selectors import match_label_selector

    sel = {
        "matchLabels": {"app": "web"},
        "matchExpressions": [
            {"key": "tier", "operator": "In", "values": ["frontend", "edge"]}
        ],
    }
    assert match_label_selector(sel, {"app": "web", "tier": "edge"})
    assert not match_label_selector(sel, {"app": "web"})          # expr fails
    assert not match_label_selector(sel, {"tier": "edge"})        # label fails
    assert not match_label_selector(sel, {"app": "db", "tier": "edge"})


def test_quantity_equivalence_through_featurization_fixture():
    """resource.Quantity equivalences (upstream apimachinery): "0.5" cpu
    == "500m", "1Gi" == str(2**30) bytes, "1e3" == "1000" — equivalent
    spellings must lower to IDENTICAL tensor rows and identical scores."""
    import numpy as np

    node = make_node("n0", cpu="4", memory="8Gi")
    spellings = [
        make_pod("a", cpu="0.5", memory="1Gi"),
        make_pod("b", cpu="500m", memory=str(2**30)),
        make_pod("c", cpu="500m", memory="1024Mi"),
    ]
    feats, res = _engine_result([node], [], spellings)
    rows = feats.pods.requests[: len(spellings)]
    np.testing.assert_array_equal(rows[0], rows[1])
    np.testing.assert_array_equal(rows[0], rows[2])
    si = res.plugin_names.index("NodeResourcesFit")
    scores = [int(res.scores[j, si, 0]) for j in range(3)]
    assert scores[0] == scores[1] == scores[2]
    # And scientific notation parses like the plain integer.
    from ksim_tpu.state.quantity import parse_quantity

    assert parse_quantity("1e3") == parse_quantity("1000")
    assert parse_quantity("1.5Gi") == parse_quantity(str(3 * 2**29))


def test_prefilter_prescore_status_plugin_sets_fixture():
    """The recorded prefilter-result-status / prescore-result maps list
    exactly the default-profile plugins whose UPSTREAM counterparts
    implement PreFilter / PreScore (resultstore records one "success"
    entry per wrapped Pre* invocation) — the byte contract the reference
    UI renders."""
    import json as _json

    from ksim_tpu.engine.annotations import (
        PRE_FILTER_STATUS_KEY,
        PRE_SCORE_RESULT_KEY,
        render_pod_results,
    )
    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer

    nodes = [make_node("n0"), make_node("n1")]
    pod = make_pod("p0")
    feats = Featurizer().featurize(nodes, [], queue_pods=[pod])
    plugins = default_plugins(feats)
    eng = Engine(feats, plugins, record="full")
    res = eng.evaluate_batch()
    anno = render_pod_results(feats, plugins, res, 0)
    prefilter = _json.loads(anno[PRE_FILTER_STATUS_KEY])
    prescore = _json.loads(anno[PRE_SCORE_RESULT_KEY])
    # Upstream v1.30 default-profile PreFilter implementers present in
    # our kernel set (CSI NodeVolumeLimits is in the filter chain too).
    assert set(prefilter) == {
        "NodeResourcesFit", "NodeAffinity", "PodTopologySpread",
        "InterPodAffinity", "NodePorts", "VolumeBinding",
        "VolumeRestrictions", "NodeVolumeLimits",
    }
    # Certain PreScore implementers must appear; plugins with no upstream
    # PreScore must not.  (VolumeBinding's PreScore is feature-gate
    # dependent upstream, so it is deliberately not pinned either way.)
    assert {
        "TaintToleration", "NodeAffinity", "PodTopologySpread",
        "InterPodAffinity", "NodeResourcesFit",
        "NodeResourcesBalancedAllocation",
    } <= set(prescore)
    assert not {"NodeName", "NodeUnschedulable", "ImageLocality"} & set(prescore)
    assert set(prefilter.values()) == {"success"}
    assert set(prescore.values()) == {"success"}


def test_single_feasible_node_skips_scoring_fixture():
    """schedule_one.go early return: with exactly ONE feasible node,
    scoring never runs — score-result / finalscore-result / prescore
    record empty maps while selected-node is still set."""
    import json as _json

    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.annotations import (
        FINAL_SCORE_RESULT_KEY,
        PRE_SCORE_RESULT_KEY,
        SCORE_RESULT_KEY,
        SELECTED_NODE_KEY,
        render_pod_results,
    )
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer

    nodes = [
        make_node("only-fit", cpu="8", memory="16Gi"),
        make_node("tiny", cpu="100m", memory="64Mi"),
    ]
    pod = make_pod("p0", cpu="1", memory="1Gi")
    feats = Featurizer().featurize(nodes, [], queue_pods=[pod])
    plugins = default_plugins(feats)
    eng = Engine(feats, plugins, record="full")
    res = eng.evaluate_batch()
    anno = render_pod_results(feats, plugins, res, 0)
    assert anno[SELECTED_NODE_KEY] == "only-fit"
    assert _json.loads(anno[SCORE_RESULT_KEY]) == {}
    assert _json.loads(anno[FINAL_SCORE_RESULT_KEY]) == {}
    assert _json.loads(anno[PRE_SCORE_RESULT_KEY]) == {}


def _policy_spread_con(**over):
    con = {
        "maxSkew": 1,
        "topologyKey": ZONE_KEY,
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "web"}},
    }
    con.update(over)
    return con


def _assert_spread_violations(nodes, bound, pod, expect):
    infos = oracle.build_node_infos(nodes, bound)
    rows = oracle.topology_spread_filter_all(pod, infos, pods_by_node(bound))
    for info, reasons in zip(infos, rows):
        assert bool(reasons) == expect[info["name"]], ("oracle", info["name"])
    _feats, res = _engine_result(nodes, bound, [pod])
    fi = res.filter_plugin_names.index("PodTopologySpread")
    for ni, info in enumerate(infos):
        got = int(res.reason_bits[0, fi, ni]) != 0
        assert got == expect[info["name"]], ("kernel", info["name"])


def test_spread_node_taints_policy_fixture():
    """nodeTaintsPolicy Honor excludes intolerably-tainted nodes from the
    domain stats (v1.30 common.go); the default Ignore counts them —
    which flips the min-match domain and with it a1's verdict."""
    nodes = [
        make_node("a1", labels={ZONE_KEY: "A"}),
        make_node(
            "b1",
            labels={ZONE_KEY: "B"},
            taints=[{"key": "dedicated", "value": "x", "effect": "NoSchedule"}],
        ),
    ]
    bound = [
        make_pod(f"w{i}", labels={"app": "web"}, node_name="a1") for i in range(2)
    ]
    for policy, expect in fx.SPREAD_TAINTS_POLICY_EXPECT.items():
        over = {} if policy == "Ignore" else {"nodeTaintsPolicy": "Honor"}
        pod = make_pod(
            "incoming",
            labels={"app": "web"},
            topology_spread_constraints=[_policy_spread_con(**over)],
        )
        _assert_spread_violations(nodes, bound, pod, expect)


def test_spread_node_affinity_policy_fixture():
    """nodeAffinityPolicy Honor (the default) excludes nodes failing the
    pod's own nodeSelector from the stats; Ignore counts them."""
    nodes = [
        make_node("a1", labels={ZONE_KEY: "A", "tier": "frontend"}),
        make_node("b1", labels={ZONE_KEY: "B"}),
    ]
    bound = [
        make_pod(f"w{i}", labels={"app": "web"}, node_name="a1") for i in range(2)
    ]
    for policy, expect in fx.SPREAD_AFFINITY_POLICY_EXPECT.items():
        over = {} if policy == "Honor" else {"nodeAffinityPolicy": "Ignore"}
        pod = make_pod(
            "incoming",
            labels={"app": "web"},
            node_selector={"tier": "frontend"},
            topology_spread_constraints=[_policy_spread_con(**over)],
        )
        _assert_spread_violations(nodes, bound, pod, expect)


def test_spread_match_label_keys_fixture():
    """matchLabelKeys folds the incoming pod's own label values into the
    selector (MatchLabelKeysInPodTopologySpread, beta/on in v1.30) —
    fully inverting the verdicts in this scenario."""
    nodes = [
        make_node("a1", labels={ZONE_KEY: "A"}),
        make_node("b1", labels={ZONE_KEY: "B"}),
    ]
    bound = [
        make_pod(f"v1-{i}", labels={"app": "web", "version": "v1"}, node_name="a1")
        for i in range(2)
    ] + [make_pod("v2-0", labels={"app": "web", "version": "v2"}, node_name="b1")]
    for mode, expect in fx.SPREAD_MATCH_LABEL_KEYS_EXPECT.items():
        over = {"matchLabelKeys": ["version"]} if mode == "with" else {}
        pod = make_pod(
            "incoming",
            labels={"app": "web", "version": "v2"},
            topology_spread_constraints=[_policy_spread_con(**over)],
        )
        _assert_spread_violations(nodes, bound, pod, expect)


def test_no_execute_taint_filter_fixture():
    """NoExecute taints reject at SCHEDULING time exactly like
    NoSchedule (taint_toleration.go Filter), with the upstream reason
    string; a toleration carrying tolerationSeconds still admits the
    pod (the seconds only govern eviction).  NoExecute is not
    PreferNoSchedule, so the score side sees zero soft taints and
    normalizes every node to 100."""
    nodes = [
        make_node("evicting", taints=[dict(fx.NO_EXECUTE_TAINT)]),
        make_node("clean"),
    ]
    plain = make_pod("plain")
    timed = make_pod("timed", tolerations=[dict(fx.NO_EXECUTE_TOLERATION)])
    feats, res = _engine_result(nodes, [], [plain, timed])
    fi = res.filter_plugin_names.index("TaintToleration")

    assert int(res.reason_bits[0, fi, 0]) != 0  # plain vs evicting
    assert int(res.reason_bits[0, fi, 1]) == 0  # plain vs clean
    # Exact upstream failure message through the kernel's reason decode.
    plugin = next(
        sp.plugin
        for sp in default_plugins(feats)
        if sp.plugin.name == "TaintToleration"
    )
    assert plugin.decode_reasons(int(res.reason_bits[0, fi, 0])) == [
        fx.NO_EXECUTE_REASON
    ]

    # tolerationSeconds does not weaken the scheduling-time match.
    assert int(res.reason_bits[1, fi, 0]) == 0
    assert int(res.reason_bits[1, fi, 1]) == 0

    # Score: no PreferNoSchedule taints anywhere -> normalized 100 on
    # every FEASIBLE cell (DefaultNormalizeScore all-100 branch).
    # Upstream only defines scores for nodes that passed filtering, so
    # the filtered (plain, evicting) cell is deliberately unasserted.
    si = res.plugin_names.index("TaintToleration")
    weight = 3  # upstream default-profile weight
    for pi in range(2):
        for ni in range(2):
            if int(res.reason_bits[pi, fi, ni]) == 0:
                assert int(res.final_scores[pi, si, ni]) == 100 * weight
