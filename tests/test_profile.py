"""Profile compilation: KubeSchedulerConfiguration -> kernel sets."""

import pytest

from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.scheduler.profile import (
    compile_configuration,
    compile_profile,
)
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod


def names(profile):
    return [n for n, _ in profile.enabled]


def test_default_profile_matches_upstream_multipoint():
    prof = compile_profile()
    assert prof.scheduler_name == "default-scheduler"
    got = dict(prof.enabled)
    assert got["TaintToleration"] == 3
    assert got["NodeAffinity"] == 2
    assert got["PodTopologySpread"] == 2
    assert got["InterPodAffinity"] == 2
    assert got["NodeResourcesFit"] == 1
    # The full default profile now compiles: nothing skipped.
    assert prof.skipped == ()
    for name in ("VolumeBinding", "VolumeZone", "VolumeRestrictions",
                 "NodeVolumeLimits"):
        assert name in got


def test_disable_and_reweight():
    prof = compile_profile({
        "plugins": {"multiPoint": {
            "disabled": [{"name": "InterPodAffinity"}],
            "enabled": [{"name": "TaintToleration", "weight": 9}],
        }},
    })
    got = dict(prof.enabled)
    assert "InterPodAffinity" not in got
    assert got["TaintToleration"] == 9


def test_disable_star_drops_all_defaults():
    prof = compile_profile({
        "plugins": {"multiPoint": {
            "disabled": [{"name": "*"}],
            "enabled": [{"name": "NodeResourcesFit", "weight": 5}],
        }},
    })
    assert prof.enabled == (("NodeResourcesFit", 5),)


def test_unknown_plugin_rejected():
    with pytest.raises(ValueError, match="unknown plugin"):
        compile_profile({
            "plugins": {"score": {"enabled": [{"name": "NoSuchPlugin"}]}},
        })


def test_plugin_args_threaded():
    prof = compile_profile({
        "pluginConfig": [
            {"name": "InterPodAffinity", "args": {"hardPodAffinityWeight": 7}},
            {"name": "NodeResourcesFit", "args": {"scoringStrategy": {
                "type": "LeastAllocated",
                "resources": [{"name": "cpu", "weight": 3}],
            }}},
        ],
    })
    assert prof.hard_pod_affinity_weight == 7
    feats = Featurizer().featurize([make_node("n")], [], queue_pods=[make_pod("p")])
    plugins = prof.plugins(feats)
    by_name = {sp.plugin.name: sp for sp in plugins}
    assert "NodeResourcesFit" in by_name
    assert by_name["TaintToleration"].weight == 3


def test_multi_profile_configuration():
    profs = compile_configuration({
        "profiles": [
            {"schedulerName": "default-scheduler"},
            {"schedulerName": "gpu-sched", "plugins": {"multiPoint": {
                "disabled": [{"name": "PodTopologySpread"}]}}},
        ],
    })
    assert [p.scheduler_name for p in profs] == ["default-scheduler", "gpu-sched"]
    assert "PodTopologySpread" not in names(profs[1])


def test_service_config_apply_and_rollback():
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    svc = SchedulerService(store)
    # Nothing applied: GET returns the scheme-defaulted document
    # (reference DefaultSchedulerConfig, scheduler/config/config.go:19-26).
    default_doc = {
        "apiVersion": "kubescheduler.config.k8s.io/v1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "default-scheduler"}],
    }
    assert svc.get_scheduler_config() == default_doc
    good = {"profiles": [{"plugins": {"multiPoint": {
        "disabled": [{"name": "InterPodAffinity"}]}}}]}
    good_doc = {
        "apiVersion": "kubescheduler.config.k8s.io/v1",
        "kind": "KubeSchedulerConfiguration",
        **good,
    }
    svc.apply_scheduler_config(good)
    assert svc.get_scheduler_config() == good_doc
    bad = {"profiles": [{"plugins": {"score": {
        "enabled": [{"name": "Bogus"}]}}}]}
    with pytest.raises(ValueError):
        svc.apply_scheduler_config(bad)
    # Rollback: previous config still active.
    assert svc.get_scheduler_config() == good_doc
    svc.reset_scheduler_config()
    assert svc.get_scheduler_config() == default_doc


def test_service_schedules_by_profile_name():
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    pod_default = make_pod("p-default")
    pod_gpu = make_pod("p-gpu")
    pod_gpu["spec"]["schedulerName"] = "gpu-sched"
    store.create("pods", pod_default)
    store.create("pods", pod_gpu)
    svc = SchedulerService(store, config={
        "profiles": [
            {"schedulerName": "default-scheduler"},
            {"schedulerName": "gpu-sched"},
        ],
    })
    placements = svc.schedule_pending()
    assert placements == {"default/p-default": "n1", "default/p-gpu": "n1"}


def test_out_of_tree_registry():
    # The WithPlugin analogue: a custom score kernel registered by name.
    class ConstantScore:
        name = "ConstantScore"

        def score(self, state, pod, aux, ok=None):
            import jax.numpy as jnp

            return jnp.full(state.valid.shape[0], 7, dtype=jnp.int32)

    def build(feats, args):
        return ScoredPlugin(ConstantScore(), filter_enabled=False)

    prof = compile_profile(
        {"plugins": {"score": {"enabled": [{"name": "ConstantScore", "weight": 2}]}}},
        registry={"ConstantScore": build},
    )
    assert ("ConstantScore", 2) in prof.enabled
    feats = Featurizer().featurize([make_node("n")], [], queue_pods=[make_pod("p")])
    plugins = prof.plugins(feats)
    assert any(sp.plugin.name == "ConstantScore" for sp in plugins)


def test_builder_import_module_allowlist(monkeypatch):
    """KSIM_ALLOWED_PLUGIN_MODULES narrows builderImport from
    all-or-nothing to an operator allowlist of module prefixes."""
    from ksim_tpu.scheduler.profile import load_plugin_import

    monkeypatch.setenv("KSIM_ALLOWED_PLUGIN_MODULES", "ksim_tpu.plugins, mycorp")
    # Allowed prefix loads (the sample plugin ships a builder).
    builder, _enc, _hooks = load_plugin_import(
        "ksim_tpu.plugins.samples.nodenumber:NODE_NUMBER_PLUGIN"
    )
    assert callable(builder)
    # Outside the allowlist: refused even though importable.
    with pytest.raises(ValueError, match="KSIM_ALLOWED_PLUGIN_MODULES"):
        load_plugin_import("json:loads")
    # Prefix match is per-component: "mycorpx" is not under "mycorp".
    with pytest.raises(ValueError, match="KSIM_ALLOWED_PLUGIN_MODULES"):
        load_plugin_import("mycorpx.evil:b")
    # Empty allowlist = no narrowing (the all-or-nothing gate upstream of
    # this function still applies).
    monkeypatch.delenv("KSIM_ALLOWED_PLUGIN_MODULES")
    builder, _enc, _hooks = load_plugin_import("json:loads")
    assert callable(builder)
