"""Independent preemption-ordering fixtures.

Upstream pickOneNodeForPreemption ranks candidate nodes (PDB criteria
degenerate without PodDisruptionBudgets) by: (1) lowest highest-victim
priority, (2) smallest victim-priority sum, (3) fewest victims,
(4) latest earliest-start-time among the HIGHEST-priority victims,
(5) first in order.  Each case below is hand-constructed so exactly one
criterion decides, with every earlier criterion tied — derived from the
upstream algorithm definition, not from this repo's implementation.
"""

from __future__ import annotations

import pytest

from ksim_tpu.scheduler.preemption import find_preemption
from tests.fixtures.preemption_victims import CASES
from tests.helpers import make_node, make_pod


def _bound(name, node, cpu, prio, start=None):
    p = make_pod(name, cpu=cpu, memory="64Mi", node_name=node, priority=prio)
    if start:
        p.setdefault("status", {})["startTime"] = start
    return p


def _preemptor(cpu):
    return make_pod("preemptor", cpu=cpu, memory="64Mi", priority=100)


def case_objects(case):
    """Build (nodes, victim_pods, preemptor_pod) JSON for one fixture
    case — shared with the device-path test (test_replay_device.py)."""
    nodes = [make_node(nm, cpu=cpu, memory="8Gi") for nm, cpu in case["nodes"]]
    victims = []
    for spec in case["victims"]:
        name, node, cpu, prio, start = spec[:5]
        created = spec[5] if len(spec) > 5 else "2024-01-01T00:00:00Z"
        p = make_pod(name, cpu=cpu, memory=None, node_name=node, priority=prio)
        p["metadata"]["creationTimestamp"] = created
        p.setdefault("status", {})["phase"] = "Running"
        if start:
            p["status"]["startTime"] = start
        victims.append(p)
    cpu, prio, policy = case["preemptor"]
    pre = make_pod("preemptor", cpu=cpu, memory=None, priority=prio)
    if policy:
        pre["spec"]["preemptionPolicy"] = policy
    return nodes, victims, pre


@pytest.mark.parametrize("case", CASES, ids=[c["name"] for c in CASES])
def test_host_oracle_matches_hand_derived_fixture(case):
    """The host victim search (oracle fit checks) lands on the
    hand-derived nominated node and the same victims in reprieve
    order."""
    nodes, victims, pre = case_objects(case)
    d = find_preemption(pre, nodes, victims)
    assert d.nominated_node == case["expected_nominated"]
    assert [v["metadata"]["name"] for v in d.victims] == case["expected_victims"]


def test_lowest_highest_victim_priority_wins():
    """Criterion 1: the node whose most important victim is LEAST
    important wins."""
    nodes = [make_node("a", cpu="1", memory="8Gi"), make_node("b", cpu="1", memory="8Gi")]
    pods = [
        _bound("va", "a", "1", prio=1),
        _bound("vb", "b", "1", prio=9),
    ]
    d = find_preemption(_preemptor("1"), nodes, pods)
    assert d.nominated_node == "a"
    assert [v["metadata"]["name"] for v in d.victims] == ["va"]


def test_smallest_priority_sum_breaks_highest_tie():
    """Criterion 2: equal highest victim priority (2 == 2); sums 3 < 4."""
    nodes = [make_node("a", cpu="2", memory="8Gi"), make_node("b", cpu="2", memory="8Gi")]
    pods = [
        _bound("a1", "a", "1", prio=2), _bound("a2", "a", "1", prio=1),
        _bound("b1", "b", "1", prio=2), _bound("b2", "b", "1", prio=2),
    ]
    d = find_preemption(_preemptor("2"), nodes, pods)
    assert d.nominated_node == "a"
    assert sorted(v["metadata"]["name"] for v in d.victims) == ["a1", "a2"]


def test_fewest_victims_breaks_sum_tie():
    """Criterion 3: highest 3 == 3, sums 4 == 4; counts 2 < 3."""
    nodes = [make_node("a", cpu="3", memory="8Gi"), make_node("b", cpu="3", memory="8Gi")]
    pods = [
        _bound("a1", "a", "1500m", prio=3), _bound("a2", "a", "1500m", prio=1),
        _bound("b1", "b", "1", prio=3), _bound("b2", "b", "1", prio=1),
        _bound("b3", "b", "1", prio=0),
    ]
    d = find_preemption(_preemptor("3"), nodes, pods)
    assert d.nominated_node == "a"
    assert len(d.victims) == 2


def test_latest_high_priority_start_breaks_count_tie():
    """Criterion 4: identical priorities and counts; the node whose
    highest-priority victim started LATEST wins (it did less work)."""
    nodes = [make_node("a", cpu="1", memory="8Gi"), make_node("b", cpu="1", memory="8Gi")]
    pods = [
        _bound("va", "a", "1", prio=5, start="2026-01-01T00:00:00Z"),
        _bound("vb", "b", "1", prio=5, start="2026-06-01T00:00:00Z"),
    ]
    d = find_preemption(_preemptor("1"), nodes, pods)
    assert d.nominated_node == "b"


def test_equal_or_higher_priority_pods_are_never_victims():
    """Only pods with priority strictly below the preemptor's are
    evictable; a node fully occupied by peers is not a candidate."""
    nodes = [make_node("a", cpu="1", memory="8Gi"), make_node("b", cpu="1", memory="8Gi")]
    pods = [
        _bound("peer", "a", "1", prio=100),   # == preemptor: untouchable
        _bound("low", "b", "1", prio=1),
    ]
    d = find_preemption(_preemptor("1"), nodes, pods)
    assert d.nominated_node == "b"
    assert [v["metadata"]["name"] for v in d.victims] == ["low"]


def test_reprieve_keeps_unneeded_victims():
    """Victim selection is minimal: once capacity fits, remaining
    lowest-priority pods are reprieved (upstream reprievePod loop)."""
    nodes = [make_node("a", cpu="3", memory="8Gi")]
    pods = [
        _bound("big", "a", "2", prio=1),
        _bound("small", "a", "1", prio=2),
    ]
    # Preemptor needs 2 cpu: evicting "big" alone suffices; "small"
    # (higher priority) is reprieved.
    d = find_preemption(_preemptor("2"), nodes, pods)
    assert d.nominated_node == "a"
    assert [v["metadata"]["name"] for v in d.victims] == ["big"]
