"""Kitchen-sink end-to-end: every resource kind and plugin family in one
product flow — import a reference-shaped snapshot, schedule, export,
and verify bindings + the complete annotation contract.

This is the "user of the reference switches over" test: one cluster
exercising resources, affinity, taints, topology spread, inter-pod
affinity, priorities, and the volume family simultaneously, through the
real HTTP surface.
"""

from __future__ import annotations

import json
import time

from ksim_tpu.engine.annotations import (
    ALL_RESULT_KEYS,
    FILTER_RESULT_KEY,
    FINAL_SCORE_RESULT_KEY,
    RESULT_HISTORY_KEY,
    SELECTED_NODE_KEY,
)
from ksim_tpu.server import DIContainer, SimulatorServer
from tests.helpers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def _snapshot() -> dict:
    nodes = [
        make_node("gpu-a", cpu="8", memory="16Gi",
                  labels={ZONE: "z1", HOST: "gpu-a", "accel": "gpu"},
                  taints=[{"key": "accel", "value": "gpu", "effect": "NoSchedule"}]),
        make_node("std-b", cpu="8", memory="16Gi", labels={ZONE: "z1", HOST: "std-b"}),
        make_node("std-c", cpu="8", memory="16Gi", labels={ZONE: "z2", HOST: "std-c"}),
    ]
    # A bound db pod (inter-pod affinity target) and a bound volume user.
    db = make_pod("db-0", cpu="1", memory="1Gi", node_name="std-b",
                  labels={"app": "db"})
    voluser = make_pod("vol-0", cpu="500m", memory="512Mi", node_name="std-c")
    voluser["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": "data-claim"}}
    ]
    pv = {
        "metadata": {"name": "pv-c", "labels": {ZONE: "z2"}},
        "spec": {
            "capacity": {"storage": "10Gi"},
            "accessModes": ["ReadWriteOnce"],
            "claimRef": {"name": "data-claim", "namespace": "default"},
            "nodeAffinity": {"required": {"nodeSelectorTerms": [
                {"matchExpressions": [
                    {"key": HOST, "operator": "In", "values": ["std-c"]}]}
            ]}},
        },
        "status": {"phase": "Bound"},
    }
    pvc = {
        "metadata": {"name": "data-claim", "namespace": "default"},
        "spec": {"accessModes": ["ReadWriteOnce"], "volumeName": "pv-c",
                 "storageClassName": "standard"},
        "status": {"phase": "Bound"},
    }
    sc = {
        "metadata": {"name": "standard"},
        "provisioner": "ebs.csi.aws.com",
        "volumeBindingMode": "WaitForFirstConsumer",
    }
    pc = {"metadata": {"name": "critical"}, "value": 1000}

    # Pending pods exercising each family:
    web1 = make_pod("web-1", cpu="1", memory="1Gi", labels={"app": "web"},
                    topology_spread_constraints=[{
                        "maxSkew": 1, "topologyKey": ZONE,
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "web"}},
                    }])
    web2 = make_pod("web-2", cpu="1", memory="1Gi", labels={"app": "web"},
                    topology_spread_constraints=[{
                        "maxSkew": 1, "topologyKey": ZONE,
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "web"}},
                    }])
    cache = make_pod("cache-1", cpu="500m", memory="512Mi")
    cache["spec"]["affinity"] = {
        "podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "db"}},
            "topologyKey": ZONE,
        }]}
    }
    gpu_job = make_pod(
        "gpu-job", cpu="1", memory="1Gi", priority=None,
        tolerations=[{"key": "accel", "operator": "Equal", "value": "gpu",
                      "effect": "NoSchedule"}],
        node_selector={"accel": "gpu"},
    )
    gpu_job["spec"]["priorityClassName"] = "critical"
    volpod = make_pod("vol-new", cpu="500m", memory="512Mi")
    volpod["spec"]["volumes"] = [
        {"name": "scratch", "persistentVolumeClaim": {"claimName": "data-claim"}}
    ]

    return {
        "nodes": nodes,
        "pods": [db, voluser, web1, web2, cache, gpu_job, volpod],
        "pvs": [pv], "pvcs": [pvc], "storageClasses": [sc],
        "priorityClasses": [pc],
        "namespaces": [{"metadata": {"name": "default"}}],
        "schedulerConfig": None,
    }


def test_kitchen_sink_end_to_end():
    import http.client

    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()

    def req(method, path, body=None):
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        c.request(method, path, json.dumps(body) if body is not None else None,
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        data = r.read()
        c.close()
        return r.status, json.loads(data) if data else None

    try:
        status, _ = req("POST", "/api/v1/import", _snapshot())
        assert status == 200
        di.scheduler_service.start()
        deadline = time.time() + 180
        bound = {}
        while time.time() < deadline:
            _, export = req("GET", "/api/v1/export")
            bound = {
                p["metadata"]["name"]: p["spec"].get("nodeName")
                for p in export["pods"]
            }
            if all(bound.values()):
                break
            time.sleep(0.3)
        # Every pod binds, respecting each family's constraints:
        assert bound["db-0"] == "std-b" and bound["vol-0"] == "std-c"  # pre-bound
        # web pods spread across zones (std-b/std-c in different zones;
        # gpu-a is untolerable for them).
        assert {bound["web-1"], bound["web-2"]} == {"std-b", "std-c"}
        # cache requires zone-affinity to db (z1): std-b (gpu-a is tainted).
        assert bound["cache-1"] == "std-b"
        # gpu-job tolerates + selects the tainted gpu node.
        assert bound["gpu-job"] == "gpu-a"
        # vol-new uses the PVC whose PV pins to std-c.
        assert bound["vol-new"] == "std-c"

        # Annotation contract: every scheduled queue pod carries ALL
        # result keys + history; filter/finalscore decode as maps.
        for p in export["pods"]:
            if p["metadata"]["name"] in ("db-0", "vol-0"):
                continue  # imported pre-bound: scheduler never touched them
            annos = p["metadata"]["annotations"]
            for key in ALL_RESULT_KEYS:
                assert key in annos, (p["metadata"]["name"], key)
            assert annos[SELECTED_NODE_KEY] == p["spec"]["nodeName"]
            assert isinstance(json.loads(annos[FILTER_RESULT_KEY]), dict)
            assert isinstance(json.loads(annos[FINAL_SCORE_RESULT_KEY]), dict)
            assert len(json.loads(annos[RESULT_HISTORY_KEY])) >= 1
    finally:
        di.scheduler_service.stop(timeout=None)
        srv.shutdown_server()
        di.shutdown()
