"""Durable job plane (round 15, ksim_tpu/jobs/journal.py +
engine/compilecache.py disk layer): crash-safe journal units
(torn-tail/corrupt-CRC bytes are HAND-WRITTEN, never derived from the
writer), persistent-executable cache units (fake disk spec, jax-free),
in-process restart recovery, the kill -9 end-to-end (slow; `make
restart-check` runs it), the SSE listener-leak regression, and the
round-16 segment-checkpoint matrix: crash at every checkpoint
boundary, corrupt-checkpoint fallback, skip containment, and the
SIGKILL-mid-run incremental resume (slow) whose suffix replay must
land the locked 6k churn counts byte-identically."""

from __future__ import annotations

import http.client
import json
import os
import socket
import subprocess
import sys
import threading
import time
import zlib

import pytest

from ksim_tpu.engine.compilecache import CompileCache
from ksim_tpu.faults import FAULTS, InjectedFault
from ksim_tpu.jobs import JobJournal, JobManager, LeasePlane
from ksim_tpu.jobs.journal import JOURNAL_NAME, _decode_line
from ksim_tpu.server import DIContainer, SimulatorServer
from tests.helpers import make_node, make_pod, sanitized_cpu_env


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    FAULTS.reset()
    yield
    FAULTS.reset()


def tiny_doc(n_pods: int = 3) -> dict:
    ops = [
        {"step": 0, "createOperation": {"object": make_node(f"n{i}", cpu="4")}}
        for i in range(2)
    ]
    ops += [
        {"step": i + 1, "createOperation": {"object": make_pod(f"p{i}", cpu="100m")}}
        for i in range(n_pods)
    ]
    return {"spec": {"scenario": {"operations": ops}}}


def _wait(job, states, deadline_s=60.0):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        if job.status()["state"] in states:
            return job.status()
        time.sleep(0.02)
    raise AssertionError(f"job {job.id} never reached {states}: {job.status()}")


# ---------------------------------------------------------------------------
# Journal units: append/replay, torn tail, corrupt CRC, compaction
# ---------------------------------------------------------------------------


def test_journal_append_replay_round_trip(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"))
    recs = [
        {"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {"x": 1}},
        {"t": "state", "id": "a", "state": "running", "ts": 1.0},
        {"t": "result", "id": "a", "result": {"podsScheduled": 3}},
        {"t": "state", "id": "a", "state": "succeeded", "ts": 2.0},
    ]
    for r in recs:
        j.append(r)
    assert JobJournal(j.path).replay() == recs
    snap = j.snapshot()
    assert snap["appends"] == 4 and snap["append_errors"] == 0


def test_journal_torn_tail_is_truncated_not_fatal(tmp_path):
    """A process killed mid-append leaves a partial final line; replay
    keeps every whole record and truncates the debris.  The torn bytes
    are hand-written — the writer never produces them."""
    j = JobJournal(str(tmp_path / "j.jsonl"))
    j.append({"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}})
    j.append({"t": "state", "id": "a", "state": "running", "ts": 1.0})
    torn = b'{"crc": 123, "rec": {"t": "state", "id": "a", "sta'
    with open(j.path, "ab") as f:
        f.write(torn)
    j2 = JobJournal(j.path)
    recs = j2.replay()
    assert [r["t"] for r in recs] == ["submit", "state"]
    assert j2.snapshot()["truncated_bytes"] == len(torn)
    # The file was repaired in place: a fresh append then full replay works.
    j2.append({"t": "state", "id": "a", "state": "succeeded", "ts": 2.0})
    assert [r["t"] for r in JobJournal(j.path).replay()] == [
        "submit", "state", "state",
    ]


def test_journal_corrupt_crc_drops_record_and_tail(tmp_path):
    """A bit-flipped record fails its checksum; the WAL contract can
    vouch for nothing after it, so the tail (even well-formed lines) is
    dropped too.  The bad line is hand-written with a deliberately
    wrong CRC."""
    j = JobJournal(str(tmp_path / "j.jsonl"))
    j.append({"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}})
    bad_rec = {"t": "state", "id": "a", "state": "running", "ts": 1.0}
    with open(j.path, "a", encoding="utf-8") as f:
        f.write(json.dumps({"crc": 1, "rec": bad_rec}) + "\n")
    j.append({"t": "state", "id": "a", "state": "succeeded", "ts": 2.0})
    j2 = JobJournal(j.path)
    recs = j2.replay()
    assert [r["t"] for r in recs] == ["submit"]
    assert j2.snapshot()["truncated_bytes"] > 0


def test_journal_garbage_and_missing_file(tmp_path):
    p = str(tmp_path / "j.jsonl")
    assert JobJournal(p).replay() == []  # missing file: empty registry
    with open(p, "w", encoding="utf-8") as f:
        f.write("not json at all\n")
    assert JobJournal(p).replay() == []


def test_journal_crc_covers_canonical_form(tmp_path):
    """A record re-serialized with different key order / whitespace
    still validates: the checksum is over the canonical JSON."""
    j = JobJournal(str(tmp_path / "j.jsonl"))
    rec = {"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {"k": 1}}
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(body.encode()) & 0xFFFFFFFF
    # Hand-write the wrapper with scrambled key order and spaces.
    with open(j.path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"rec": rec, "crc": crc}, indent=None) + "\n")
    assert JobJournal(j.path).replay() == [rec]


def test_journal_compaction_bounds_file(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"), max_bytes=256)
    for i in range(50):
        j.append({"t": "state", "id": "a", "state": "running", "ts": float(i)})
    live = [{"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}}]
    assert j.maybe_compact(lambda: live) is True
    assert j.snapshot()["compactions"] == 1
    assert os.path.getsize(j.path) < 256
    assert JobJournal(j.path).replay() == live
    # Under the bound: no-op.
    assert j.maybe_compact(lambda: live) is False


def test_journal_append_fault_raises_and_counts(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"))
    FAULTS.arm("jobs.journal_append", "call:1")
    with pytest.raises(InjectedFault):
        j.append({"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}})
    assert j.snapshot()["append_errors"] == 1
    j.append({"t": "state", "id": "a", "state": "running", "ts": 1.0})
    assert [r["t"] for r in JobJournal(j.path).replay()] == ["state"]


# ---------------------------------------------------------------------------
# CompileCache disk layer (fake disk spec — stdlib-only, no jax)
# ---------------------------------------------------------------------------


class FakeDisk:
    """Duck-typed disk spec: 'serializes' to a fixed blob; load/invoke
    count calls so tests can tell the disk path from the compile path."""

    def __init__(self, path, token="tok-1", blob=b"fake-executable-bytes"):
        self.path = str(path)
        self.token = token
        self.blob = blob
        self.loads = 0
        self.invokes = 0
        self.fail_invoke = False

    def load(self, blob):
        assert blob == self.blob
        self.loads += 1
        return ("exec", blob)

    def invoke(self, exec_obj):
        self.invokes += 1
        if self.fail_invoke:
            raise RuntimeError("platform mismatch")
        return "disk-result"

    def serialize(self):
        return self.blob


def test_disk_store_then_warm_load(tmp_path):
    path = tmp_path / "e.aot"
    cc1 = CompileCache()
    d1 = FakeDisk(path)
    out = cc1.run("k", lambda: "compiled-result", disk=d1)
    assert out == "compiled-result"
    s1 = cc1.snapshot()
    assert s1["disk_misses"] == 1 and s1["disk_stores"] == 1
    header, _, blob = path.read_bytes().partition(b"\n")
    meta = json.loads(header)
    assert meta["v"] == 1 and meta["key"] == "tok-1"
    assert meta["crc"] == (zlib.crc32(blob) & 0xFFFFFFFF)
    # A "restarted process": fresh cache, same file -> no compile.
    cc2 = CompileCache()
    d2 = FakeDisk(path)
    out = cc2.run("k", lambda: pytest.fail("compiled on a disk hit"), disk=d2)
    assert out == "disk-result"
    s2 = cc2.snapshot()
    assert s2["disk_hits"] == 1 and d2.loads == 1 and d2.invokes == 1


def test_disk_corrupt_blob_evicted_and_recompiled(tmp_path):
    path = tmp_path / "e.aot"
    cc = CompileCache()
    cc.run("k", lambda: "r", disk=FakeDisk(path))
    raw = bytearray(path.read_bytes())
    raw[-3] ^= 0xFF  # hand-flip a blob byte: CRC must catch it
    path.write_bytes(bytes(raw))
    cc2 = CompileCache()
    out = cc2.run("k", lambda: "recompiled", disk=FakeDisk(path))
    assert out == "recompiled"
    s = cc2.snapshot()
    assert s["disk_evictions"] == 1 and s["disk_hits"] == 0
    # The eviction unlinked, then the store re-persisted a good entry.
    assert s["disk_stores"] == 1
    assert json.loads(path.read_bytes().partition(b"\n")[0])["v"] == 1


def test_disk_garbage_header_evicted(tmp_path):
    path = tmp_path / "e.aot"
    path.write_bytes(b"\x00\x01 not a header\nblob")
    cc = CompileCache()
    assert cc.run("k", lambda: "r", disk=FakeDisk(path)) == "r"
    assert cc.snapshot()["disk_evictions"] == 1


def test_disk_headerless_file_evicted(tmp_path):
    """No newline at all — the partition finds no separator."""
    path = tmp_path / "e.aot"
    path.write_bytes(b'{"v": 1, "crc": 0, "key": "tok-1"}')
    cc = CompileCache()
    assert cc.run("k", lambda: "r", disk=FakeDisk(path)) == "r"
    assert cc.snapshot()["disk_evictions"] == 1


def test_disk_key_mismatch_evicted(tmp_path):
    """A stale jaxlib (or hash-colliding path) changes the token; the
    entry must never reach the deserializer."""
    path = tmp_path / "e.aot"
    cc = CompileCache()
    cc.run("k", lambda: "r", disk=FakeDisk(path, token="jax-0.4.0|cpu|sig"))
    cc2 = CompileCache()
    d = FakeDisk(path, token="jax-9.9.9|cpu|sig")
    assert cc2.run("k", lambda: "recompiled", disk=d) == "recompiled"
    assert cc2.snapshot()["disk_evictions"] == 1
    assert d.loads == 0  # blob never handed to load()


def test_disk_exec_failure_evicts_and_falls_back(tmp_path):
    path = tmp_path / "e.aot"
    cc = CompileCache()
    cc.run("k", lambda: "r", disk=FakeDisk(path))
    cc2 = CompileCache()
    d = FakeDisk(path)
    d.fail_invoke = True
    assert cc2.run("k", lambda: "recompiled", disk=d) == "recompiled"
    s = cc2.snapshot()
    assert s["disk_evictions"] == 1 and d.loads == 1 and d.invokes == 1


def test_disk_serialize_none_skips_store(tmp_path):
    path = tmp_path / "e.aot"
    cc = CompileCache()
    d = FakeDisk(path)
    d.serialize = lambda: None  # non-exportable plan
    assert cc.run("k", lambda: "r", disk=d) == "r"
    assert cc.snapshot()["disk_stores"] == 0
    assert not path.exists()


# ---------------------------------------------------------------------------
# Manager recovery (in-process restarts: new JobManager over the same dir)
# ---------------------------------------------------------------------------


def test_restart_serves_result_byte_identically(tmp_path):
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded", "failed"})
    state, result, _ = job.result_view()
    assert state == "succeeded"
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    j2 = jm2.get(job.id)
    assert j2 is not None
    state2, result2, _ = j2.result_view()
    assert state2 == "succeeded"
    assert json.dumps(result2, sort_keys=True) == json.dumps(result, sort_keys=True)
    jm2.shutdown()


def test_di_container_builds_manager_eagerly_when_jobs_dir_set(
    tmp_path, monkeypatch
):
    """A restarted SERVER must recover before the first tenant request:
    the DI container's lazy job-plane build (a classic-surface
    optimization) is skipped when KSIM_JOBS_DIR is set, otherwise a
    journaled result 404s until something happens to force the manager
    into existence — the gap an end-to-end restart drive caught."""
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded", "failed"})
    jm.shutdown()
    monkeypatch.setenv("KSIM_JOBS_DIR", str(tmp_path))
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")
    di = DIContainer()
    try:
        recovered = di.job_manager_if_built
        assert recovered is not None  # built (and recovered) at construction
        j2 = recovered.get(job.id)
        assert j2 is not None
        state, result, _ = j2.result_view()
        assert state == "succeeded"
        assert result["result"]["podsScheduled"] == 3
    finally:
        di.shutdown()


def test_restart_marks_unfinished_jobs_interrupted(tmp_path):
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())  # no workers: stays queued forever
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    j2 = jm2.get(job.id)
    state, result, error = j2.result_view()
    assert state == "interrupted"
    assert result is None and "restart" in error
    jm2.shutdown()


def test_resume_reenqueues_unfinished_jobs(tmp_path):
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    jm.shutdown()
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    j2 = jm2.get(job.id)
    final = _wait(j2, {"succeeded", "failed", "interrupted"})
    assert final["state"] == "succeeded", final
    assert j2.result_view()[1]["result"]["podsScheduled"] == 3
    jm2.shutdown()


def test_interrupted_then_resume_still_reenqueues(tmp_path):
    """Regression: a job journaled as `interrupted` by a resume-less
    restart must still be reachable by a LATER restart with
    KSIM_JOBS_RESUME=1 — interrupted is terminal for serving, not for
    the resume policy."""
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.get(job.id).result_view()[0] == "interrupted"
    jm2.shutdown()
    jm3 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    final = _wait(jm3.get(job.id), {"succeeded", "failed", "interrupted"})
    assert final["state"] == "succeeded", final
    jm3.shutdown()


def test_recovery_survives_torn_tail(tmp_path):
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded"})
    jm.shutdown()
    torn = b'{"crc": 99, "rec": {"t": "subm'  # the kill -9 artifact
    with open(os.path.join(str(tmp_path), JOURNAL_NAME), "ab") as f:
        f.write(torn)
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.get(job.id).result_view()[0] == "succeeded"
    assert jm2.snapshot()["journal"]["truncated_bytes"] == len(torn)
    jm2.shutdown()


def test_submit_append_fault_fails_one_job_not_registry(tmp_path):
    """An armed jobs.journal_append failure fails the ONE submission
    whose record was lost; the manager and later submissions are
    untouched."""
    FAULTS.arm("jobs.journal_append", "first:1", exc=OSError)
    # workers=0: the submit-path append is the only journal writer, so
    # the armed first:1 lands on it deterministically.
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    state, _, error = job.result_view()
    assert state == "failed" and "journal append failed" in error
    job2 = jm.submit(tiny_doc())
    assert job2.status()["state"] == "queued"
    jm.shutdown()
    # The failed job's submit record never landed: a restart only
    # knows the successful one — and resume runs it to completion.
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    assert jm2.get(job.id) is None
    assert _wait(jm2.get(job2.id), {"succeeded", "failed"})["state"] == "succeeded"
    jm2.shutdown()


def test_replay_fault_starts_empty_registry_not_crash(tmp_path):
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded"})
    jm.shutdown()
    FAULTS.arm("jobs.journal_replay", "call:1", exc=OSError)
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.jobs() == []  # lost the registry, kept the process
    jm2.shutdown()


def test_cancel_is_journaled(tmp_path):
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    assert jm.cancel(job.id) == "cancelled"
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.get(job.id).result_view()[0] == "cancelled"
    jm2.shutdown()


# ---------------------------------------------------------------------------
# The kill -9: a real process dies mid-job, the next one recovers
# ---------------------------------------------------------------------------

_CRASH_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
from ksim_tpu.jobs import JobManager
from tests.helpers import make_node, make_pod

# 200 one-pod steps (enough to still be mid-run when killed) on nodes
# big enough that every pod fits — the resumed run must schedule ALL.
ops = [
    {"step": 0, "createOperation": {"object": make_node(f"n{i}", cpu="32")}}
    for i in range(2)
]
ops += [
    {"step": i + 1, "createOperation": {"object": make_pod(f"p{i}", cpu="100m")}}
    for i in range(200)
]
doc = {"spec": {"scenario": {"operations": ops}}}

jm = JobManager(workers=1, queue_limit=8, jobs_dir=sys.argv[1])
job = jm.submit(doc)
while job.status()["state"] == "queued":
    time.sleep(0.01)
print("RUNNING", job.id, flush=True)
time.sleep(600)  # parent kills -9 long before this returns
"""


@pytest.mark.slow
def test_sigkill_mid_job_then_restart_recovers(tmp_path):
    """The acceptance scenario: kill -9 a server mid-job; a restarted
    manager over the same KSIM_JOBS_DIR replays the journal, marks the
    died-mid-run job `interrupted`, and a resume restart re-runs it to
    completion."""
    proc = subprocess.Popen(
        [sys.executable, "-c", _CRASH_CHILD, str(tmp_path)],
        env=sanitized_cpu_env(),
        cwd="/root/repo",
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("RUNNING"), line
        jid = line.split()[1]
        time.sleep(0.2)  # let a few steps land in the running state
    finally:
        proc.kill()  # SIGKILL: no atexit, no flush, no goodbye
        proc.wait()
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    state, result, error = jm.get(jid).result_view()
    assert state == "interrupted" and result is None
    assert "restart" in error
    jm.shutdown()
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    final = _wait(jm2.get(jid), {"succeeded", "failed", "interrupted"}, 120.0)
    assert final["state"] == "succeeded", final
    assert jm2.get(jid).result_view()[1]["result"]["podsScheduled"] == 200
    jm2.shutdown()


# ---------------------------------------------------------------------------
# Segment checkpoints + incremental resume (round 16, docs/jobs.md)
# ---------------------------------------------------------------------------


def churn_device_doc(
    seed: int = 3, n_nodes: int = 32, n_steps: int = 40, **sim_extra
) -> dict:
    """A device-replay churn job long enough to cross several segment
    commits (K=16 steps each): step 0 bootstraps the fleet, then
    ``n_steps`` churn steps of 20 events."""
    from ksim_tpu.scenario import churn_scenario, spec_from_operations

    ops = list(
        churn_scenario(
            seed,
            n_nodes=n_nodes,
            n_events=n_nodes + 20 * n_steps,
            ops_per_step=20,
        )
    )
    sim = {"deviceReplay": True, "podBucketMin": 64, **sim_extra}
    return {"spec": {"simulator": sim, "scenario": spec_from_operations(ops)}}


def _locked_counts(result_doc: dict) -> dict:
    """The byte-identical slice of a job result: everything except the
    wall-clock fields (a resumed run's ``wallSeconds`` covers only its
    own suffix replay — documented, and exactly the point)."""
    return {
        k: v for k, v in result_doc["result"].items() if k != "wallSeconds"
    }


def _run_checkpointed(tmp_path, doc, **mgr_kw) -> tuple[str, dict]:
    """Run one job to completion with checkpoints on; return
    (job_id, final result doc)."""
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        checkpoint_every=mgr_kw.pop("checkpoint_every", 1), **mgr_kw,
    )
    job = jm.submit(doc)
    final = _wait(job, {"succeeded", "failed"}, 300.0)
    assert final["state"] == "succeeded", final
    _, result, _ = job.result_view()
    jm.shutdown()
    return job.id, result


def _rewrite_journal(tmp_path, recs) -> str:
    """Replace the dir's journal with exactly ``recs`` (each re-appended
    through the writer, so CRCs are valid)."""
    path = os.path.join(str(tmp_path), JOURNAL_NAME)
    os.unlink(path)
    j = JobJournal(path)
    for r in recs:
        j.append(r)
    return path


def test_checkpoints_append_at_cadence_and_throttle(tmp_path):
    """checkpoint_every=1 appends one record per committed segment with
    monotonically increasing cursors; a coarser cadence appends strictly
    fewer.  The newest checkpoint's segment shows in job status."""
    jid, _ = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    cks = [r for r in recs if r["t"] == "checkpoint"]
    assert len(cks) >= 2
    cursors = [c["cursor"] for c in cks]
    assert cursors == sorted(set(cursors))
    assert all(c["id"] == jid for c in cks)
    for c in cks:
        assert c["store"]["objects"]["nodes"]  # exact state rode along
        assert "pass_count" in c["service"]
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm.get(jid).status()["checkpoint_segment"] is None  # terminal: not carried
    jm.shutdown()

    coarse = tmp_path / "coarse"
    coarse.mkdir()
    _run_checkpointed(coarse, churn_device_doc(), checkpoint_every=2)
    coarse_cks = [
        r
        for r in JobJournal(os.path.join(str(coarse), JOURNAL_NAME)).replay()
        if r["t"] == "checkpoint"
    ]
    assert 0 < len(coarse_cks) < len(cks)


def test_resume_from_every_checkpoint_boundary_byte_identical(tmp_path):
    """The crash matrix: truncate the journal right after EACH
    checkpoint record in turn (the crash window between the checkpoint
    append and the next journaled transition), resume, and require the
    final counts byte-identical to the uninterrupted run — with the
    suffix replay doing strictly less work the later the crash."""
    jid, full = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    ck_idx = [i for i, r in enumerate(recs) if r["t"] == "checkpoint"]
    assert len(ck_idx) >= 2
    total_events = full["result"]["eventsApplied"]
    replayed = []
    for idx in ck_idx:
        _rewrite_journal(tmp_path, recs[: idx + 1])
        jm = JobManager(
            workers=1, queue_limit=8, jobs_dir=str(tmp_path),
            resume=True, checkpoint_every=0,
        )
        job = jm.get(jid)
        final = _wait(job, {"succeeded", "failed", "interrupted"}, 300.0)
        assert final["state"] == "succeeded", final
        _, res, _ = job.result_view()
        assert _locked_counts(res) == _locked_counts(full)
        assert res["resume"]["cursor"] == recs[idx]["cursor"]
        assert final["resumed_from"] == recs[idx]["segment"]
        replayed.append(res["resume"]["eventsReplayed"])
        jm.shutdown()
    # Later checkpoints leave strictly less to replay, and even the
    # earliest resume did less work than a from-scratch replay.
    assert replayed == sorted(replayed, reverse=True)
    assert replayed[0] < total_events


def test_resume_with_torn_tail_after_checkpoint(tmp_path):
    """kill -9 mid-append AFTER the last checkpoint: the torn bytes are
    dropped by the journal's tail rule and the checkpoint restores."""
    jid, full = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    last_ck = max(i for i, r in enumerate(recs) if r["t"] == "checkpoint")
    path = _rewrite_journal(tmp_path, recs[: last_ck + 1])
    with open(path, "ab") as f:
        f.write(b'{"crc": 7, "rec": {"t": "checkpo')  # the kill artifact
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        resume=True, checkpoint_every=0,
    )
    final = _wait(jm.get(jid), {"succeeded", "failed", "interrupted"}, 300.0)
    assert final["state"] == "succeeded", final
    assert _locked_counts(jm.get(jid).result_view()[1]) == _locked_counts(full)
    jm.shutdown()


def test_corrupt_checkpoint_falls_back_to_previous(tmp_path):
    """A checkpoint whose CRC validates but whose payload no longer
    restores (bit rot past the line hash, a format drift) must fall
    back to the PREVIOUS checkpoint, not fail the job or restart it
    from scratch."""
    jid, full = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    ck_idx = [i for i, r in enumerate(recs) if r["t"] == "checkpoint"]
    assert len(ck_idx) >= 2
    keep = recs[: ck_idx[-1] + 1]
    keep[-1] = dict(keep[-1], store={"not": "a store"})  # re-CRC'd on append
    _rewrite_journal(tmp_path, keep)
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        resume=True, checkpoint_every=0,
    )
    job = jm.get(jid)
    final = _wait(job, {"succeeded", "failed", "interrupted"}, 300.0)
    assert final["state"] == "succeeded", final
    assert final["resumed_from"] == recs[ck_idx[-2]]["segment"]
    assert _locked_counts(job.result_view()[1]) == _locked_counts(full)
    jm.shutdown()


def test_restore_fault_falls_back_to_scratch(tmp_path):
    """Every checkpoint unusable (armed jobs.checkpoint_restore): the
    resumed job replays from scratch and still lands the identical
    result — restore is an optimization, never a correctness gate."""
    jid, full = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    last_ck = max(i for i, r in enumerate(recs) if r["t"] == "checkpoint")
    _rewrite_journal(tmp_path, recs[: last_ck + 1])
    FAULTS.arm("jobs.checkpoint_restore", "always")
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        resume=True, checkpoint_every=0,
    )
    job = jm.get(jid)
    final = _wait(job, {"succeeded", "failed", "interrupted"}, 300.0)
    assert final["state"] == "succeeded", final
    _, res, _ = job.result_view()
    assert _locked_counts(res) == _locked_counts(full)
    assert "resume" not in res and final["resumed_from"] is None
    jm.shutdown()


def test_resume_across_spec_change_refuses_checkpoints(tmp_path):
    """The code half of "Resume across a config change" (docs/jobs.md;
    the doc half shipped in round 17): every checkpoint record carries
    the simulator-spec hash, and a resumed job whose spec CHANGED
    refuses the mismatched records — replaying from scratch under the
    new config instead of silently installing carries the old config
    produced.  The refusal must be total (resumed_from None) even
    though structurally valid checkpoints sit right there in the
    journal."""
    jid, full = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    cks = [r for r in recs if r["t"] == "checkpoint"]
    assert cks and all(r.get("spec") for r in cks)
    assert len({r["spec"] for r in cks}) == 1  # one spec, one hash
    last_ck = max(i for i, r in enumerate(recs) if r["t"] == "checkpoint")
    keep = recs[: last_ck + 1]
    for i, r in enumerate(keep):
        if r["t"] == "submit":
            doc = json.loads(json.dumps(r["doc"]))
            # The config change: a knob that reshapes the pod batching
            # but not the locked counts.
            doc["spec"]["simulator"]["podBucketMin"] = 128
            keep[i] = dict(r, doc=doc)
    _rewrite_journal(tmp_path, keep)
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        resume=True, checkpoint_every=0,
    )
    job = jm.get(jid)
    final = _wait(job, {"succeeded", "failed", "interrupted"}, 300.0)
    assert final["state"] == "succeeded", final
    _, res, _ = job.result_view()
    assert "resume" not in res and final["resumed_from"] is None
    assert _locked_counts(res) == _locked_counts(full)
    jm.shutdown()


def test_checkpoint_append_fault_never_fails_the_job(tmp_path):
    """The best-effort contract: an armed jobs.checkpoint_append (or
    any snapshot failure) skips checkpoints with a counted event; the
    run itself completes untouched."""
    FAULTS.arm("jobs.checkpoint_append", "always", exc=OSError)
    jid, result = _run_checkpointed(tmp_path, churn_device_doc())
    assert result["result"]["podsScheduled"] > 0
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    assert not [r for r in recs if r["t"] == "checkpoint"]


def test_checkpoint_max_bytes_skips_oversized_snapshots(tmp_path):
    """A snapshot over KSIM_JOBS_CHECKPOINT_MAX_BYTES is skipped (the
    journal must not bloat unboundedly); the job still succeeds."""
    jid, result = _run_checkpointed(
        tmp_path, churn_device_doc(), checkpoint_max_bytes=64
    )
    assert result["result"]["podsScheduled"] > 0
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    assert not [r for r in recs if r["t"] == "checkpoint"]


def test_compaction_keeps_newest_checkpoint_for_live_jobs(tmp_path, monkeypatch):
    """The compaction snapshot re-emits exactly ONE checkpoint — the
    newest — for each non-terminal job (a terminal job's checkpoints
    are dead weight and dropped)."""
    jid, _ = _run_checkpointed(tmp_path, churn_device_doc())
    recs = JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).replay()
    ck = [r for r in recs if r["t"] == "checkpoint"]
    assert len(ck) >= 2
    # Crash right after the last checkpoint; the resumed-but-unserved
    # job is LIVE (workers=0: it stays queued).
    last_ck = max(i for i, r in enumerate(recs) if r["t"] == "checkpoint")
    _rewrite_journal(tmp_path, recs[: last_ck + 1])
    monkeypatch.setenv("KSIM_JOBS_JOURNAL_MAX_BYTES", "1")  # force compaction
    jm = JobManager(
        workers=0, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    live = [r for r in jm._journal_records() if r["t"] == "checkpoint"]
    assert len(live) == 1 and live[0]["seq"] == ck[-1]["seq"]
    assert jm._journal.maybe_compact(jm._journal_records) is True
    jm.shutdown()
    # The compacted journal still resumes from that checkpoint.
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        resume=True, checkpoint_every=0,
    )
    final = _wait(jm2.get(jid), {"succeeded", "failed", "interrupted"}, 300.0)
    assert final["state"] == "succeeded", final
    assert final["resumed_from"] == ck[-1]["segment"]
    jm2.shutdown()


def test_resumed_job_sse_backlog_is_gap_free(tmp_path):
    """Satellite regression: a tenant reconnecting to a resumed job's
    SSE stream must see the PRE-restart lifecycle (queued→running)
    replayed ahead of the re-enqueue, not a log that starts mid-life."""
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    jm.shutdown()
    # The crashed worker's journal footprint: it had started running.
    JobJournal(os.path.join(str(tmp_path), JOURNAL_NAME)).append(
        {"t": "state", "id": job.id, "state": "running", "ts": 1.0}
    )
    jm2 = JobManager(
        workers=0, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    j2 = jm2.get(job.id)
    with j2._cond:
        events = [dict(e) for e in j2._events]
    states = [
        (e["state"], e.get("recovered", False), e.get("resumed", False))
        for e in events
        if e.get("event") == "state"
    ]
    assert states == [
        ("running", True, False),  # the journaled pre-crash history
        ("queued", False, True),  # then the re-enqueue
    ]
    jm2.shutdown()


_CKPT_CRASH_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
from ksim_tpu.jobs import JobManager
from ksim_tpu.scenario import churn_scenario, spec_from_operations

# The locked 6k churn prefix (repo CLAUDE.md), as a device-replay job.
ops = list(churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100))
doc = {"spec": {
    "simulator": {
        "deviceReplay": True, "maxPodsPerPass": 1024, "podBucketMin": 128,
    },
    "scenario": spec_from_operations(ops),
}}
jm = JobManager(workers=1, queue_limit=8, jobs_dir=sys.argv[1],
                checkpoint_every=1)
job = jm.submit(doc)
while True:
    st = job.status()
    if st["checkpoint_segment"] is not None:
        break
    if st["state"] in ("succeeded", "failed"):
        print("FINISHED-EARLY", st["state"], flush=True)
        sys.exit(2)
    time.sleep(0.05)
print("CHECKPOINTED", job.id, flush=True)
time.sleep(600)  # parent kills -9 long before this returns
"""


@pytest.mark.slow
def test_sigkill_mid_run_resumes_suffix_with_locked_counts(tmp_path):
    """The round-16 acceptance scenario: kill -9 a worker after its
    first durable checkpoint; a KSIM_JOBS_RESUME=1 restart restores the
    checkpoint and replays ONLY the remaining suffix — strictly fewer
    events than the full stream — landing the locked 6k churn counts
    (2524/471, seed 0, 2000 nodes) byte-identically."""
    proc = subprocess.Popen(
        [sys.executable, "-c", _CKPT_CRASH_CHILD, str(tmp_path)],
        env=sanitized_cpu_env(),
        cwd="/root/repo",
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("CHECKPOINTED"), line
        jid = line.split()[1]
    finally:
        proc.kill()  # SIGKILL: no atexit, no flush, no goodbye
        proc.wait()
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        resume=True, checkpoint_every=0,
    )
    job = jm.get(jid)
    assert job is not None
    final = _wait(job, {"succeeded", "failed", "interrupted"}, 300.0)
    assert final["state"] == "succeeded", final
    _, res, _ = job.result_view()
    assert res["result"]["eventsApplied"] == 6430
    assert (
        res["result"]["podsScheduled"],
        res["result"]["unschedulableAttempts"],
    ) == (2524, 471)
    assert final["resumed_from"] is not None
    assert 0 < res["resume"]["eventsReplayed"] < 6430
    jm.shutdown()


# ---------------------------------------------------------------------------
# SSE hardening: aborted readers must not leak listeners
# ---------------------------------------------------------------------------


def test_sse_aborted_reader_releases_listener(monkeypatch):
    """An EventSource that vanishes mid-stream (socket torn down, no
    graceful close) must be detected by the heartbeat write and its
    listener count released — the pre-round-15 handler leaked the
    thread until the job finished."""
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")  # job stays queued: stream idles
    monkeypatch.setenv("KSIM_JOBS_SSE_HEARTBEAT_S", "0.2")
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        conn.request(
            "POST", "/api/v1/jobs", json.dumps(tiny_doc()),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        jid = json.loads(resp.read())["id"]
        assert resp.status == 202
        conn.close()

        raw = socket.create_connection(("127.0.0.1", srv.port), timeout=30)
        raw.sendall(
            f"GET /api/v1/jobs/{jid}/events HTTP/1.1\r\n"
            f"Host: 127.0.0.1\r\n\r\n".encode()
        )
        first = raw.recv(4096)  # headers + the replayed queued event
        assert b"text/event-stream" in first

        job = di.job_manager_if_built.get(jid)
        deadline = time.monotonic() + 10
        while job.status()["sse_listeners"] != 1:
            assert time.monotonic() < deadline, job.status()
            time.sleep(0.02)

        # Keepalives flow while the stream idles (nothing new to send).
        buf = b""
        deadline = time.monotonic() + 10
        while b": keepalive" not in buf:
            assert time.monotonic() < deadline, buf
            buf += raw.recv(4096)

        # The abort: RST the socket, no FIN handshake, reader gone.
        raw.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            __import__("struct").pack("ii", 1, 0),
        )
        raw.close()
        deadline = time.monotonic() + 10
        while job.status()["sse_listeners"] != 0:
            assert time.monotonic() < deadline, job.status()
            time.sleep(0.05)
    finally:
        srv.shutdown_server()
        di.shutdown()

# ---------------------------------------------------------------------------
# Multi-worker fleet (round 20): the lease plane, the shared journal,
# and kill-a-worker fail-over (docs/jobs.md "Multi-worker fleet")
# ---------------------------------------------------------------------------


class _FakeClock:
    """Injectable clock for the lease protocol tests — expiry windows
    advance exactly when the test says so."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def _planes(tmp_path, clock, *workers, lease_s=10.0):
    return [
        LeasePlane(str(tmp_path), worker=w, lease_s=lease_s, clock=clock)
        for w in workers
    ]


def test_lease_claim_race_exactly_one_winner(tmp_path):
    """Two members claiming the same job simultaneously serialize on
    the exclusive flock and exactly one wins (flock is per-open-
    description, so two planes in one process exclude each other)."""
    a, b = _planes(tmp_path, _FakeClock(), "wA", "wB")
    barrier = threading.Barrier(2)
    results: dict[str, "dict | None"] = {}

    def race(name, plane):
        barrier.wait()
        results[name] = plane.claim("job-0")

    threads = [
        threading.Thread(target=race, args=p) for p in (("wA", a), ("wB", b))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    winners = [n for n, r in results.items() if r is not None]
    assert len(winners) == 1, results
    lease = a.leases()["job-0"]
    assert lease["worker"] == winners[0] and lease["epoch"] == 1
    counters = a.counters()
    assert counters[winners[0]]["claims"] == 1
    assert sum(c["claims"] for c in counters.values()) == 1


def test_lease_double_claim_refused_and_own_reclaim_idempotent(tmp_path):
    clock = _FakeClock()
    a, b = _planes(tmp_path, clock, "wA", "wB")
    first = a.claim("job-0")
    assert first is not None and first["epoch"] == 1
    assert b.claim("job-0") is None  # live lease, someone else's
    again = a.claim("job-0")  # the owner re-claiming is a no-op
    assert again is not None and again["epoch"] == 1
    assert a.counters()["wA"]["claims"] == 1  # ... and appended nothing


def test_lease_renew_extends_expiry_and_skips_not_ours(tmp_path):
    clock = _FakeClock()
    (a,) = _planes(tmp_path, clock, "wA")
    a.claim("job-0")
    before = a.leases()["job-0"]["expires"]
    clock.t += 5.0
    assert a.renew(["job-0", "job-ghost"]) == 1  # the ghost is skipped
    assert a.leases()["job-0"]["expires"] == before + 5.0
    assert a.counters()["wA"]["renews"] == 1


def test_expired_lease_takeover_bumps_epoch_and_counters(tmp_path):
    """The fail-over path: a lease whose owner stopped renewing ages
    out, the next claimer wins with a bumped epoch, the takeover is
    charged to the claimer and the expiry to the worker that lost."""
    clock = _FakeClock()
    a, b = _planes(tmp_path, clock, "wA", "wB")
    a.claim("job-0")
    clock.t += 5.0
    assert b.claim("job-0") is None  # still live: refused
    clock.t += 6.0  # past the 10s lease: the fail-over window
    won = b.claim("job-0")
    assert won is not None and won["epoch"] == 2
    counters = b.counters()
    assert counters["wB"]["claims"] == 1 and counters["wB"]["takeovers"] == 1
    assert counters["wA"]["expired"] == 1
    # The deposed owner cannot renew its way back in.
    assert a.renew(["job-0"]) == 0


def test_released_lease_is_never_reclaimable(tmp_path):
    """released == finished (releases happen only after the terminal
    record is durable), so no amount of clock is ever enough."""
    clock = _FakeClock()
    a, b = _planes(tmp_path, clock, "wA", "wB")
    a.claim("job-0")
    a.release("job-0")
    clock.t += 10_000.0
    assert b.claim("job-0") is None
    assert a.claim("job-0") is None  # not even the old owner


def test_lease_compaction_preserves_leases_and_counters(tmp_path):
    """Compaction rewrites newest-record-per-id + a trailing counters
    snapshot; the fold over the compacted file must be identical —
    including the released tombstones the claim protocol depends on."""
    clock = _FakeClock()
    a, b = _planes(tmp_path, clock, "wA", "wB")
    a.claim("job-0")
    b.claim("job-1")
    for _ in range(50):
        clock.t += 1.0
        a.renew(["job-0"])
        b.renew(["job-1"])
    b.release("job-1")
    before_leases, before_counters = a.leases(), a.counters()
    size = os.path.getsize(a.path)
    assert a.maybe_compact(max_bytes=1) is True
    assert os.path.getsize(a.path) < size
    assert a.leases() == before_leases
    assert a.counters() == before_counters
    # A brand-new member folds the compacted file to the same view,
    # and the released job stays unclaimable.
    (c,) = _planes(tmp_path, clock, "wC")
    assert c.leases() == before_leases
    assert c.claim("job-1") is None


# -- the shared journal: satellite regression (multi-appender safety) -------


def test_shared_journal_interleaved_appenders_record_atomic(tmp_path):
    """Two handles interleaving appends — including a
    multi-hundred-KB checkpoint-sized record — leave a file where every
    line decodes independently: the single-``os.write``-per-record rule
    means appenders interleave only at record granularity.  Checked on
    the raw BYTES, not through replay."""
    path = os.path.join(str(tmp_path), JOURNAL_NAME)
    j1 = JobJournal(path, shared=True)
    j2 = JobJournal(path, shared=True)
    big = {
        "t": "checkpoint", "id": "job-0", "segment": 1, "cursor": 16,
        "store": {"blob": "x" * 300_000},
    }
    j1.append({"t": "submit", "id": "job-0", "ordinal": 0, "doc": {}})
    j2.append({"t": "state", "id": "job-0", "state": "running"})
    j1.append(big)
    j2.append({"t": "state", "id": "job-0", "state": "succeeded"})
    j1.append({"t": "result", "id": "job-0", "result": {"ok": 1}})
    with open(path, "r", encoding="utf-8", newline="") as f:
        lines = f.readlines()
    assert len(lines) == 5
    recs = [_decode_line(ln) for ln in lines]
    assert all(r is not None for r in recs)
    assert [r["t"] for r in recs] == [
        "submit", "state", "checkpoint", "state", "result",
    ]
    assert recs[2]["store"]["blob"] == big["store"]["blob"]
    # A third handle replays the merged stream intact.
    assert len(JobJournal(path, shared=True).replay()) == 5


def test_shared_journal_concurrent_append_stress(tmp_path):
    """The actual race: two handles appending concurrently from two
    threads (flock is per-open-description, so this exercises the real
    cross-process exclusion).  Nothing torn, nothing lost."""
    path = os.path.join(str(tmp_path), JOURNAL_NAME)
    j1 = JobJournal(path, shared=True)
    j2 = JobJournal(path, shared=True)

    def pump(j, tag):
        for i in range(100):
            j.append({
                "t": "state", "id": f"{tag}-{i}", "state": "running",
                "pad": "y" * (4096 if i % 7 == 0 else 8),
            })

    threads = [
        threading.Thread(target=pump, args=p)
        for p in ((j1, "one"), (j2, "two"))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    recs = JobJournal(path, shared=True).replay()
    assert len(recs) == 200
    assert {r["id"] for r in recs} == {
        f"{tag}-{i}" for tag in ("one", "two") for i in range(100)
    }


def test_shared_compaction_folds_other_appenders_records(tmp_path):
    """The satellite regression: pre-round-20 compaction rewrote the
    journal from the LOCAL registry snapshot, silently dropping records
    a second process appended.  Shared compaction folds the file's own
    records — keeping the other appender's newest state/checkpoint, the
    record types it does not understand, and never stranding the other
    appender on the replaced inode."""
    path = os.path.join(str(tmp_path), JOURNAL_NAME)
    j1 = JobJournal(path, shared=True, max_bytes=256)
    j2 = JobJournal(path, shared=True)
    j1.append({"t": "submit", "id": "job-0", "ordinal": 0, "doc": {"spec": {}}})
    for i in range(20):
        j2.append({"t": "state", "id": "job-0", "state": "running", "ts": i})
    j2.append({"t": "checkpoint", "id": "job-0", "segment": 3, "cursor": 48})
    j2.append({"t": "checkpoint", "id": "job-0", "segment": 7, "cursor": 112})
    j2.append({"t": "fleet-extension", "custom": True})  # unknown type
    assert j1.maybe_compact(lambda: []) is True  # snapshot_fn IGNORED
    # The second appender keeps appending: per-record re-open lands the
    # write on the NEW inode, not the compacted-away one.
    j2.append({"t": "state", "id": "job-0", "state": "succeeded", "ts": 99})
    recs = JobJournal(path, shared=True).replay()
    assert [r["t"] for r in recs] == [
        "submit", "state", "checkpoint", "fleet-extension", "state",
    ]
    assert recs[1]["ts"] == 19  # newest pre-compaction state won
    assert recs[2]["segment"] == 7  # newest checkpoint won, older shed
    assert recs[4]["ts"] == 99


# -- the fleet loop in-process: frontdoor mirror + worker adoption ----------


def test_fleet_frontdoor_worker_lifecycle_in_process(tmp_path):
    """One frontdoor + one worker manager over a shared dir: the
    frontdoor journals the submit, the worker claims/runs/releases, and
    the frontdoor mirror folds state, result, events, owner and lease
    back for status/result/SSE."""
    fd = JobManager(
        workers=0, queue_limit=8, jobs_dir=str(tmp_path),
        role="frontdoor", worker_id="fd", lease_s=3.0, poll_s=0.1,
    )
    wk = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        role="worker", worker_id="w1", lease_s=3.0, poll_s=0.1,
    )
    try:
        job = fd.submit(tiny_doc())
        final = _wait(job, {"succeeded", "failed"}, 120.0)
        assert final["state"] == "succeeded", final
        assert final["owner"] == "w1"
        assert final["lease"]["epoch"] == 1
        state, res, _ = job.result_view()
        assert state == "succeeded"
        assert res["result"]["podsScheduled"] == 3  # ran on the worker
        # The mirrored SSE ring: state + progress events crossed the
        # manager boundary via the per-job event file.
        deadline = time.monotonic() + 15
        while True:
            evs, _, done = job.events_since(0, 0)
            kinds = [e["event"] for e in evs]
            if done and "state" in kinds and "progress" in kinds:
                break
            assert time.monotonic() < deadline, kinds
            time.sleep(0.05)
        flt = fd.snapshot()["fleet"]
        assert flt["role"] == "frontdoor" and flt["worker_id"] == "fd"
        assert flt["workers"]["w1"]["claims"] == 1
        wflt = wk.snapshot()["fleet"]
        assert wflt["role"] == "worker"
        # Released after the terminal record — on the worker's NEXT poll
        # tick, which the mirrored SSE completion above does not order
        # against, so wait for it rather than racing it.
        deadline = time.monotonic() + 15
        while wk.snapshot()["fleet"]["owned"]:
            assert time.monotonic() < deadline, wk.snapshot()["fleet"]
            time.sleep(0.05)
    finally:
        wk.shutdown()
        fd.shutdown()


def test_fleet_cancel_routes_to_owning_worker(tmp_path):
    """A cancel submitted at the front door reaches the owning worker
    through the journal's cancel record and stops the run mid-flight."""
    ops = [
        {"step": 0, "createOperation": {"object": make_node(f"n{i}", cpu="32")}}
        for i in range(2)
    ]
    ops += [
        {"step": i + 1, "createOperation": {"object": make_pod(f"p{i}", cpu="100m")}}
        for i in range(400)
    ]
    doc = {"spec": {"scenario": {"operations": ops}}}
    fd = JobManager(
        workers=0, queue_limit=8, jobs_dir=str(tmp_path),
        role="frontdoor", worker_id="fd", lease_s=3.0, poll_s=0.05,
    )
    wk = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        role="worker", worker_id="w1", lease_s=3.0, poll_s=0.05,
    )
    try:
        job = fd.submit(doc)
        running = _wait(job, {"running", "succeeded", "failed"}, 60.0)
        assert running["state"] == "running", running
        fd.cancel(job.id)
        final = _wait(job, {"cancelled", "succeeded", "failed"}, 60.0)
        assert final["state"] == "cancelled", final
        assert final["owner"] == "w1"
    finally:
        wk.shutdown()
        fd.shutdown()


# -- kill-a-worker chaos: the acceptance scenario ---------------------------


_SIX_K_DOC_SRC = """
from ksim_tpu.scenario import churn_scenario, spec_from_operations

ops = list(churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100))
doc = {"spec": {
    "simulator": {
        "deviceReplay": True, "maxPodsPerPass": 1024, "podBucketMin": 128,
    },
    "scenario": spec_from_operations(ops),
}}
"""


def _six_k_doc() -> dict:
    ns: dict = {}
    exec(_SIX_K_DOC_SRC, ns)
    return ns["doc"]


def _spawn_fleet_worker(tmp_path, worker_id: str) -> subprocess.Popen:
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ksim_tpu.jobs",
            "--dir", str(tmp_path), "--worker-id", worker_id,
            "--workers", "1",
        ],
        env=sanitized_cpu_env({
            "KSIM_WORKERS_LEASE_S": "4",
            "KSIM_WORKERS_HEARTBEAT_S": "1",
            "KSIM_WORKERS_POLL_S": "0.2",
            "KSIM_JOBS_CHECKPOINT_EVERY": "1",
        }),
        cwd="/root/repo",
        stdout=subprocess.PIPE,
        text=True,
    )
    line = proc.stdout.readline()
    assert line.strip() == f"READY {worker_id}", line
    return proc


@pytest.mark.slow
def test_fleet_sigkill_owner_fails_over_with_locked_counts(tmp_path):
    """The round-20 acceptance scenario (`make restart-check`): a fleet
    of two worker PROCESSES behind an in-process front door; SIGKILL
    the worker that owns the locked 6k churn job after its first
    durable checkpoint.  The survivor's claim succeeds once the lease
    expires (takeover, epoch 2), it adopts the job from the journal
    fold and resumes from the newest checkpoint — landing 2524/471
    byte-identically with strictly fewer events replayed, exactly one
    result record, and the takeover/expiry charged to the right
    workers."""
    procs = {
        "wA": _spawn_fleet_worker(tmp_path, "wA"),
        "wB": _spawn_fleet_worker(tmp_path, "wB"),
    }
    fd = JobManager(
        workers=0, queue_limit=8, jobs_dir=str(tmp_path),
        role="frontdoor", worker_id="fd", lease_s=4.0, poll_s=0.2,
    )
    try:
        job = fd.submit(_six_k_doc())
        # Wait for an owner AND its first durable checkpoint (both
        # mirrored into frontdoor status) — the kill window where
        # fail-over must resume, not restart.
        deadline = time.monotonic() + 300
        while True:
            st = job.status()
            assert st["state"] not in ("succeeded", "failed"), st
            if st["owner"] in procs and st["checkpoint_segment"] is not None:
                break
            assert time.monotonic() < deadline, st
            time.sleep(0.1)
        owner, survivor = st["owner"], ("wA" if st["owner"] == "wB" else "wB")
        procs[owner].kill()  # SIGKILL: no atexit, no flush, no goodbye
        procs[owner].wait()

        final = _wait(job, {"succeeded", "failed", "interrupted"}, 600.0)
        assert final["state"] == "succeeded", final
        assert final["owner"] == survivor
        assert final["lease"]["epoch"] >= 2
        _, res, _ = job.result_view()
        assert res["result"]["eventsApplied"] == 6430
        assert (
            res["result"]["podsScheduled"],
            res["result"]["unschedulableAttempts"],
        ) == (2524, 471)
        assert 0 < res["resume"]["eventsReplayed"] < 6430
        # Zero lost, zero duplicated: exactly one result record made it
        # into the shared journal.
        recs = JobJournal(
            os.path.join(str(tmp_path), JOURNAL_NAME), shared=True
        ).replay()
        assert sum(1 for r in recs if r["t"] == "result") == 1
        counters = fd.snapshot()["fleet"]["workers"]
        assert counters[survivor]["takeovers"] == 1
        assert counters[owner]["expired"] == 1
    finally:
        for proc in procs.values():
            proc.kill()
            proc.wait()
        fd.shutdown()
