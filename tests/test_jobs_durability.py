"""Durable job plane (round 15, ksim_tpu/jobs/journal.py +
engine/compilecache.py disk layer): crash-safe journal units
(torn-tail/corrupt-CRC bytes are HAND-WRITTEN, never derived from the
writer), persistent-executable cache units (fake disk spec, jax-free),
in-process restart recovery, the kill -9 end-to-end (slow; `make
restart-check` runs it), and the SSE listener-leak regression."""

from __future__ import annotations

import http.client
import json
import os
import socket
import subprocess
import sys
import time
import zlib

import pytest

from ksim_tpu.engine.compilecache import CompileCache
from ksim_tpu.faults import FAULTS, InjectedFault
from ksim_tpu.jobs import JobJournal, JobManager
from ksim_tpu.jobs.journal import JOURNAL_NAME
from ksim_tpu.server import DIContainer, SimulatorServer
from tests.helpers import make_node, make_pod, sanitized_cpu_env


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    FAULTS.reset()
    yield
    FAULTS.reset()


def tiny_doc(n_pods: int = 3) -> dict:
    ops = [
        {"step": 0, "createOperation": {"object": make_node(f"n{i}", cpu="4")}}
        for i in range(2)
    ]
    ops += [
        {"step": i + 1, "createOperation": {"object": make_pod(f"p{i}", cpu="100m")}}
        for i in range(n_pods)
    ]
    return {"spec": {"scenario": {"operations": ops}}}


def _wait(job, states, deadline_s=60.0):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        if job.status()["state"] in states:
            return job.status()
        time.sleep(0.02)
    raise AssertionError(f"job {job.id} never reached {states}: {job.status()}")


# ---------------------------------------------------------------------------
# Journal units: append/replay, torn tail, corrupt CRC, compaction
# ---------------------------------------------------------------------------


def test_journal_append_replay_round_trip(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"))
    recs = [
        {"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {"x": 1}},
        {"t": "state", "id": "a", "state": "running", "ts": 1.0},
        {"t": "result", "id": "a", "result": {"podsScheduled": 3}},
        {"t": "state", "id": "a", "state": "succeeded", "ts": 2.0},
    ]
    for r in recs:
        j.append(r)
    assert JobJournal(j.path).replay() == recs
    snap = j.snapshot()
    assert snap["appends"] == 4 and snap["append_errors"] == 0


def test_journal_torn_tail_is_truncated_not_fatal(tmp_path):
    """A process killed mid-append leaves a partial final line; replay
    keeps every whole record and truncates the debris.  The torn bytes
    are hand-written — the writer never produces them."""
    j = JobJournal(str(tmp_path / "j.jsonl"))
    j.append({"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}})
    j.append({"t": "state", "id": "a", "state": "running", "ts": 1.0})
    torn = b'{"crc": 123, "rec": {"t": "state", "id": "a", "sta'
    with open(j.path, "ab") as f:
        f.write(torn)
    j2 = JobJournal(j.path)
    recs = j2.replay()
    assert [r["t"] for r in recs] == ["submit", "state"]
    assert j2.snapshot()["truncated_bytes"] == len(torn)
    # The file was repaired in place: a fresh append then full replay works.
    j2.append({"t": "state", "id": "a", "state": "succeeded", "ts": 2.0})
    assert [r["t"] for r in JobJournal(j.path).replay()] == [
        "submit", "state", "state",
    ]


def test_journal_corrupt_crc_drops_record_and_tail(tmp_path):
    """A bit-flipped record fails its checksum; the WAL contract can
    vouch for nothing after it, so the tail (even well-formed lines) is
    dropped too.  The bad line is hand-written with a deliberately
    wrong CRC."""
    j = JobJournal(str(tmp_path / "j.jsonl"))
    j.append({"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}})
    bad_rec = {"t": "state", "id": "a", "state": "running", "ts": 1.0}
    with open(j.path, "a", encoding="utf-8") as f:
        f.write(json.dumps({"crc": 1, "rec": bad_rec}) + "\n")
    j.append({"t": "state", "id": "a", "state": "succeeded", "ts": 2.0})
    j2 = JobJournal(j.path)
    recs = j2.replay()
    assert [r["t"] for r in recs] == ["submit"]
    assert j2.snapshot()["truncated_bytes"] > 0


def test_journal_garbage_and_missing_file(tmp_path):
    p = str(tmp_path / "j.jsonl")
    assert JobJournal(p).replay() == []  # missing file: empty registry
    with open(p, "w", encoding="utf-8") as f:
        f.write("not json at all\n")
    assert JobJournal(p).replay() == []


def test_journal_crc_covers_canonical_form(tmp_path):
    """A record re-serialized with different key order / whitespace
    still validates: the checksum is over the canonical JSON."""
    j = JobJournal(str(tmp_path / "j.jsonl"))
    rec = {"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {"k": 1}}
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(body.encode()) & 0xFFFFFFFF
    # Hand-write the wrapper with scrambled key order and spaces.
    with open(j.path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"rec": rec, "crc": crc}, indent=None) + "\n")
    assert JobJournal(j.path).replay() == [rec]


def test_journal_compaction_bounds_file(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"), max_bytes=256)
    for i in range(50):
        j.append({"t": "state", "id": "a", "state": "running", "ts": float(i)})
    live = [{"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}}]
    assert j.maybe_compact(lambda: live) is True
    assert j.snapshot()["compactions"] == 1
    assert os.path.getsize(j.path) < 256
    assert JobJournal(j.path).replay() == live
    # Under the bound: no-op.
    assert j.maybe_compact(lambda: live) is False


def test_journal_append_fault_raises_and_counts(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"))
    FAULTS.arm("jobs.journal_append", "call:1")
    with pytest.raises(InjectedFault):
        j.append({"t": "submit", "id": "a", "ordinal": 0, "priority": 0, "doc": {}})
    assert j.snapshot()["append_errors"] == 1
    j.append({"t": "state", "id": "a", "state": "running", "ts": 1.0})
    assert [r["t"] for r in JobJournal(j.path).replay()] == ["state"]


# ---------------------------------------------------------------------------
# CompileCache disk layer (fake disk spec — stdlib-only, no jax)
# ---------------------------------------------------------------------------


class FakeDisk:
    """Duck-typed disk spec: 'serializes' to a fixed blob; load/invoke
    count calls so tests can tell the disk path from the compile path."""

    def __init__(self, path, token="tok-1", blob=b"fake-executable-bytes"):
        self.path = str(path)
        self.token = token
        self.blob = blob
        self.loads = 0
        self.invokes = 0
        self.fail_invoke = False

    def load(self, blob):
        assert blob == self.blob
        self.loads += 1
        return ("exec", blob)

    def invoke(self, exec_obj):
        self.invokes += 1
        if self.fail_invoke:
            raise RuntimeError("platform mismatch")
        return "disk-result"

    def serialize(self):
        return self.blob


def test_disk_store_then_warm_load(tmp_path):
    path = tmp_path / "e.aot"
    cc1 = CompileCache()
    d1 = FakeDisk(path)
    out = cc1.run("k", lambda: "compiled-result", disk=d1)
    assert out == "compiled-result"
    s1 = cc1.snapshot()
    assert s1["disk_misses"] == 1 and s1["disk_stores"] == 1
    header, _, blob = path.read_bytes().partition(b"\n")
    meta = json.loads(header)
    assert meta["v"] == 1 and meta["key"] == "tok-1"
    assert meta["crc"] == (zlib.crc32(blob) & 0xFFFFFFFF)
    # A "restarted process": fresh cache, same file -> no compile.
    cc2 = CompileCache()
    d2 = FakeDisk(path)
    out = cc2.run("k", lambda: pytest.fail("compiled on a disk hit"), disk=d2)
    assert out == "disk-result"
    s2 = cc2.snapshot()
    assert s2["disk_hits"] == 1 and d2.loads == 1 and d2.invokes == 1


def test_disk_corrupt_blob_evicted_and_recompiled(tmp_path):
    path = tmp_path / "e.aot"
    cc = CompileCache()
    cc.run("k", lambda: "r", disk=FakeDisk(path))
    raw = bytearray(path.read_bytes())
    raw[-3] ^= 0xFF  # hand-flip a blob byte: CRC must catch it
    path.write_bytes(bytes(raw))
    cc2 = CompileCache()
    out = cc2.run("k", lambda: "recompiled", disk=FakeDisk(path))
    assert out == "recompiled"
    s = cc2.snapshot()
    assert s["disk_evictions"] == 1 and s["disk_hits"] == 0
    # The eviction unlinked, then the store re-persisted a good entry.
    assert s["disk_stores"] == 1
    assert json.loads(path.read_bytes().partition(b"\n")[0])["v"] == 1


def test_disk_garbage_header_evicted(tmp_path):
    path = tmp_path / "e.aot"
    path.write_bytes(b"\x00\x01 not a header\nblob")
    cc = CompileCache()
    assert cc.run("k", lambda: "r", disk=FakeDisk(path)) == "r"
    assert cc.snapshot()["disk_evictions"] == 1


def test_disk_headerless_file_evicted(tmp_path):
    """No newline at all — the partition finds no separator."""
    path = tmp_path / "e.aot"
    path.write_bytes(b'{"v": 1, "crc": 0, "key": "tok-1"}')
    cc = CompileCache()
    assert cc.run("k", lambda: "r", disk=FakeDisk(path)) == "r"
    assert cc.snapshot()["disk_evictions"] == 1


def test_disk_key_mismatch_evicted(tmp_path):
    """A stale jaxlib (or hash-colliding path) changes the token; the
    entry must never reach the deserializer."""
    path = tmp_path / "e.aot"
    cc = CompileCache()
    cc.run("k", lambda: "r", disk=FakeDisk(path, token="jax-0.4.0|cpu|sig"))
    cc2 = CompileCache()
    d = FakeDisk(path, token="jax-9.9.9|cpu|sig")
    assert cc2.run("k", lambda: "recompiled", disk=d) == "recompiled"
    assert cc2.snapshot()["disk_evictions"] == 1
    assert d.loads == 0  # blob never handed to load()


def test_disk_exec_failure_evicts_and_falls_back(tmp_path):
    path = tmp_path / "e.aot"
    cc = CompileCache()
    cc.run("k", lambda: "r", disk=FakeDisk(path))
    cc2 = CompileCache()
    d = FakeDisk(path)
    d.fail_invoke = True
    assert cc2.run("k", lambda: "recompiled", disk=d) == "recompiled"
    s = cc2.snapshot()
    assert s["disk_evictions"] == 1 and d.loads == 1 and d.invokes == 1


def test_disk_serialize_none_skips_store(tmp_path):
    path = tmp_path / "e.aot"
    cc = CompileCache()
    d = FakeDisk(path)
    d.serialize = lambda: None  # non-exportable plan
    assert cc.run("k", lambda: "r", disk=d) == "r"
    assert cc.snapshot()["disk_stores"] == 0
    assert not path.exists()


# ---------------------------------------------------------------------------
# Manager recovery (in-process restarts: new JobManager over the same dir)
# ---------------------------------------------------------------------------


def test_restart_serves_result_byte_identically(tmp_path):
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded", "failed"})
    state, result, _ = job.result_view()
    assert state == "succeeded"
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    j2 = jm2.get(job.id)
    assert j2 is not None
    state2, result2, _ = j2.result_view()
    assert state2 == "succeeded"
    assert json.dumps(result2, sort_keys=True) == json.dumps(result, sort_keys=True)
    jm2.shutdown()


def test_di_container_builds_manager_eagerly_when_jobs_dir_set(
    tmp_path, monkeypatch
):
    """A restarted SERVER must recover before the first tenant request:
    the DI container's lazy job-plane build (a classic-surface
    optimization) is skipped when KSIM_JOBS_DIR is set, otherwise a
    journaled result 404s until something happens to force the manager
    into existence — the gap an end-to-end restart drive caught."""
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded", "failed"})
    jm.shutdown()
    monkeypatch.setenv("KSIM_JOBS_DIR", str(tmp_path))
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")
    di = DIContainer()
    try:
        recovered = di.job_manager_if_built
        assert recovered is not None  # built (and recovered) at construction
        j2 = recovered.get(job.id)
        assert j2 is not None
        state, result, _ = j2.result_view()
        assert state == "succeeded"
        assert result["result"]["podsScheduled"] == 3
    finally:
        di.shutdown()


def test_restart_marks_unfinished_jobs_interrupted(tmp_path):
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())  # no workers: stays queued forever
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    j2 = jm2.get(job.id)
    state, result, error = j2.result_view()
    assert state == "interrupted"
    assert result is None and "restart" in error
    jm2.shutdown()


def test_resume_reenqueues_unfinished_jobs(tmp_path):
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    jm.shutdown()
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    j2 = jm2.get(job.id)
    final = _wait(j2, {"succeeded", "failed", "interrupted"})
    assert final["state"] == "succeeded", final
    assert j2.result_view()[1]["result"]["podsScheduled"] == 3
    jm2.shutdown()


def test_interrupted_then_resume_still_reenqueues(tmp_path):
    """Regression: a job journaled as `interrupted` by a resume-less
    restart must still be reachable by a LATER restart with
    KSIM_JOBS_RESUME=1 — interrupted is terminal for serving, not for
    the resume policy."""
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.get(job.id).result_view()[0] == "interrupted"
    jm2.shutdown()
    jm3 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    final = _wait(jm3.get(job.id), {"succeeded", "failed", "interrupted"})
    assert final["state"] == "succeeded", final
    jm3.shutdown()


def test_recovery_survives_torn_tail(tmp_path):
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded"})
    jm.shutdown()
    torn = b'{"crc": 99, "rec": {"t": "subm'  # the kill -9 artifact
    with open(os.path.join(str(tmp_path), JOURNAL_NAME), "ab") as f:
        f.write(torn)
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.get(job.id).result_view()[0] == "succeeded"
    assert jm2.snapshot()["journal"]["truncated_bytes"] == len(torn)
    jm2.shutdown()


def test_submit_append_fault_fails_one_job_not_registry(tmp_path):
    """An armed jobs.journal_append failure fails the ONE submission
    whose record was lost; the manager and later submissions are
    untouched."""
    FAULTS.arm("jobs.journal_append", "first:1", exc=OSError)
    # workers=0: the submit-path append is the only journal writer, so
    # the armed first:1 lands on it deterministically.
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    state, _, error = job.result_view()
    assert state == "failed" and "journal append failed" in error
    job2 = jm.submit(tiny_doc())
    assert job2.status()["state"] == "queued"
    jm.shutdown()
    # The failed job's submit record never landed: a restart only
    # knows the successful one — and resume runs it to completion.
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    assert jm2.get(job.id) is None
    assert _wait(jm2.get(job2.id), {"succeeded", "failed"})["state"] == "succeeded"
    jm2.shutdown()


def test_replay_fault_starts_empty_registry_not_crash(tmp_path):
    jm = JobManager(workers=1, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    _wait(job, {"succeeded"})
    jm.shutdown()
    FAULTS.arm("jobs.journal_replay", "call:1", exc=OSError)
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.jobs() == []  # lost the registry, kept the process
    jm2.shutdown()


def test_cancel_is_journaled(tmp_path):
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    job = jm.submit(tiny_doc())
    assert jm.cancel(job.id) == "cancelled"
    jm.shutdown()
    jm2 = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    assert jm2.get(job.id).result_view()[0] == "cancelled"
    jm2.shutdown()


# ---------------------------------------------------------------------------
# The kill -9: a real process dies mid-job, the next one recovers
# ---------------------------------------------------------------------------

_CRASH_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
from ksim_tpu.jobs import JobManager
from tests.helpers import make_node, make_pod

# 200 one-pod steps (enough to still be mid-run when killed) on nodes
# big enough that every pod fits — the resumed run must schedule ALL.
ops = [
    {"step": 0, "createOperation": {"object": make_node(f"n{i}", cpu="32")}}
    for i in range(2)
]
ops += [
    {"step": i + 1, "createOperation": {"object": make_pod(f"p{i}", cpu="100m")}}
    for i in range(200)
]
doc = {"spec": {"scenario": {"operations": ops}}}

jm = JobManager(workers=1, queue_limit=8, jobs_dir=sys.argv[1])
job = jm.submit(doc)
while job.status()["state"] == "queued":
    time.sleep(0.01)
print("RUNNING", job.id, flush=True)
time.sleep(600)  # parent kills -9 long before this returns
"""


@pytest.mark.slow
def test_sigkill_mid_job_then_restart_recovers(tmp_path):
    """The acceptance scenario: kill -9 a server mid-job; a restarted
    manager over the same KSIM_JOBS_DIR replays the journal, marks the
    died-mid-run job `interrupted`, and a resume restart re-runs it to
    completion."""
    proc = subprocess.Popen(
        [sys.executable, "-c", _CRASH_CHILD, str(tmp_path)],
        env=sanitized_cpu_env(),
        cwd="/root/repo",
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("RUNNING"), line
        jid = line.split()[1]
        time.sleep(0.2)  # let a few steps land in the running state
    finally:
        proc.kill()  # SIGKILL: no atexit, no flush, no goodbye
        proc.wait()
    jm = JobManager(workers=0, queue_limit=8, jobs_dir=str(tmp_path))
    state, result, error = jm.get(jid).result_view()
    assert state == "interrupted" and result is None
    assert "restart" in error
    jm.shutdown()
    jm2 = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path), resume=True
    )
    final = _wait(jm2.get(jid), {"succeeded", "failed", "interrupted"}, 120.0)
    assert final["state"] == "succeeded", final
    assert jm2.get(jid).result_view()[1]["result"]["podsScheduled"] == 200
    jm2.shutdown()


# ---------------------------------------------------------------------------
# SSE hardening: aborted readers must not leak listeners
# ---------------------------------------------------------------------------


def test_sse_aborted_reader_releases_listener(monkeypatch):
    """An EventSource that vanishes mid-stream (socket torn down, no
    graceful close) must be detected by the heartbeat write and its
    listener count released — the pre-round-15 handler leaked the
    thread until the job finished."""
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")  # job stays queued: stream idles
    monkeypatch.setenv("KSIM_JOBS_SSE_HEARTBEAT_S", "0.2")
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        conn.request(
            "POST", "/api/v1/jobs", json.dumps(tiny_doc()),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        jid = json.loads(resp.read())["id"]
        assert resp.status == 202
        conn.close()

        raw = socket.create_connection(("127.0.0.1", srv.port), timeout=30)
        raw.sendall(
            f"GET /api/v1/jobs/{jid}/events HTTP/1.1\r\n"
            f"Host: 127.0.0.1\r\n\r\n".encode()
        )
        first = raw.recv(4096)  # headers + the replayed queued event
        assert b"text/event-stream" in first

        job = di.job_manager_if_built.get(jid)
        deadline = time.monotonic() + 10
        while job.status()["sse_listeners"] != 1:
            assert time.monotonic() < deadline, job.status()
            time.sleep(0.02)

        # Keepalives flow while the stream idles (nothing new to send).
        buf = b""
        deadline = time.monotonic() + 10
        while b": keepalive" not in buf:
            assert time.monotonic() < deadline, buf
            buf += raw.recv(4096)

        # The abort: RST the socket, no FIN handshake, reader gone.
        raw.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            __import__("struct").pack("ii", 1, 0),
        )
        raw.close()
        deadline = time.monotonic() + 10
        while job.status()["sse_listeners"] != 0:
            assert time.monotonic() < deadline, job.status()
            time.sleep(0.05)
    finally:
        srv.shutdown_server()
        di.shutdown()
