"""Snapshot export/import: reference JSON-schema compatibility."""

import json

from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.snapshot import SnapshotService
from tests.helpers import make_node, make_pod


def _store_with_content() -> ClusterStore:
    s = ClusterStore()
    s.create("nodes", make_node("n1"))
    s.create("pods", make_pod("p1", labels={"app": "web"}))
    s.create("pods", make_pod("p2", labels={"app": "db"}))
    s.create("namespaces", {"metadata": {"name": "default"}})
    s.create("namespaces", {"metadata": {"name": "kube-system"}})
    s.create("priorityclasses", {"metadata": {"name": "high"}, "value": 100})
    s.create(
        "priorityclasses",
        {"metadata": {"name": "system-cluster-critical"}, "value": 2000000000},
    )
    return s


def test_snap_shape_matches_reference_schema():
    svc = SnapshotService(_store_with_content())
    snap = svc.snap()
    # Exact key set of ResourcesForSnap (reference snapshot.go:33-42).
    assert set(snap.keys()) == {
        "pods", "nodes", "pvs", "pvcs", "storageClasses",
        "priorityClasses", "schedulerConfig", "namespaces",
    }
    assert len(snap["pods"]) == 2
    assert len(snap["nodes"]) == 1


def test_snap_excludes_system_pcs_and_kube_namespaces():
    snap = SnapshotService(_store_with_content()).snap()
    assert [p["metadata"]["name"] for p in snap["priorityClasses"]] == ["high"]
    assert [n["metadata"]["name"] for n in snap["namespaces"]] == ["default"]


def test_snap_label_selector_filtering():
    snap = SnapshotService(_store_with_content()).snap(
        {"matchLabels": {"app": "web"}}
    )
    assert [p["metadata"]["name"] for p in snap["pods"]] == ["p1"]
    assert snap["nodes"] == []  # nodes lack the label


def test_load_round_trip():
    exported = SnapshotService(_store_with_content()).export_json()
    dst = ClusterStore()
    SnapshotService(dst).import_json(exported)
    assert [n["metadata"]["name"] for n in dst.list("nodes")] == ["n1"]
    assert len(dst.list("pods")) == 2
    # UIDs are re-assigned on load, not carried in.
    src_uid = json.loads(exported)["pods"][0]["metadata"].get("uid")
    dst_uid = dst.list("pods")[0]["metadata"]["uid"]
    assert dst_uid and dst_uid != src_uid


def test_load_fixes_pv_claim_ref_uid():
    dst = ClusterStore()
    SnapshotService(dst).load(
        {
            "pvcs": [{"metadata": {"name": "claim", "namespace": "apps", "uid": "old-pvc-uid"}}],
            "pvs": [{
                "metadata": {"name": "vol"},
                "spec": {"claimRef": {"name": "claim", "namespace": "apps", "uid": "old-pvc-uid"}},
                "status": {"phase": "Bound"},
            }, {
                "metadata": {"name": "vol-avail"},
                "spec": {"claimRef": {"name": "claim", "namespace": "apps", "uid": "old-pvc-uid"}},
                "status": {"phase": "Available"},
            }, {
                "metadata": {"name": "vol-orphan"},
                "spec": {"claimRef": {"name": "gone", "namespace": "apps", "uid": "stale"}},
                "status": {"phase": "Bound"},
            }],
        }
    )
    pvc = dst.get("persistentvolumeclaims", "claim", "apps")
    pv = dst.get("persistentvolumes", "vol")
    assert pvc["metadata"]["uid"] != "old-pvc-uid"  # re-assigned on load
    assert pv["spec"]["claimRef"]["uid"] == pvc["metadata"]["uid"]
    # Non-Bound PVs are untouched; missing PVC clears the stale UID.
    assert dst.get("persistentvolumes", "vol-avail")["spec"]["claimRef"]["uid"] == "old-pvc-uid"
    assert dst.get("persistentvolumes", "vol-orphan")["spec"]["claimRef"]["uid"] is None


def test_load_skips_kube_namespaces():
    dst = ClusterStore()
    SnapshotService(dst).load(
        {"namespaces": [
            {"metadata": {"name": "kube-system"}},
            {"metadata": {"name": "apps"}},
        ]}
    )
    assert [n["metadata"]["name"] for n in dst.list("namespaces")] == ["apps"]


def test_load_skips_system_priority_classes():
    dst = ClusterStore()
    SnapshotService(dst).load(
        {"priorityClasses": [
            {"metadata": {"name": "system-node-critical"}, "value": 1},
            {"metadata": {"name": "normal"}, "value": 5},
        ]}
    )
    assert [p["metadata"]["name"] for p in dst.list("priorityclasses")] == ["normal"]


def test_load_snapshot_applies_scheduler_config():
    # Round-1 verdict weak #4: loading a snapshot carrying a
    # schedulerConfig through a live SchedulerService must apply it (the
    # reference calls RestartScheduler after load, snapshot.go:202-219).
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore
    from ksim_tpu.state.snapshot import SnapshotService

    store = ClusterStore()
    sched = SchedulerService(store, config={})
    svc = SnapshotService(store, scheduler_service=sched)
    cfg = {
        "apiVersion": "kubescheduler.config.k8s.io/v1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "my-sched"}],
    }
    svc.load({"nodes": [], "pods": [], "schedulerConfig": cfg})
    assert sched.get_scheduler_config() == cfg
    # ignore_scheduler_configuration leaves the config untouched.
    svc.load(
        {"schedulerConfig": {"profiles": [{"schedulerName": "other"}]}},
        ignore_scheduler_configuration=True,
    )
    assert sched.get_scheduler_config() == cfg
