"""KubeApiSource against a stub kube-apiserver (plain HTTP list+watch).

The reference tests its syncer against dynamicFake clientsets with
convergence polling (reference simulator/syncer/syncer_test.go:18-120);
here the fake is a real HTTP server speaking the apiserver's list/watch
wire protocol, so the adapter's streaming, resume, and 410-relist paths
are all exercised for real.
"""

from __future__ import annotations

import base64
import copy
import json
import re
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ksim_tpu.errors import InvalidConfigError
from ksim_tpu.state.cluster import ADDED, DELETED, MODIFIED, ClusterStore
from ksim_tpu.syncer import Syncer
from ksim_tpu.syncer.kubeapi import _API_PATHS, KubeApiSource, load_kubeconfig
from tests.helpers import make_node, make_pod

_PATH_KINDS = {path: kind for kind, path in _API_PATHS.items()}


class _ApiState:
    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.rv = 0
        self.objects: dict[str, dict[str, dict]] = {k: {} for k in _API_PATHS}
        self.events: list[tuple[int, str, str, dict]] = []
        self.compacted = 0  # watches resuming from rv < compacted get 410
        self.generation = 0  # bump to force active watch handlers to close
        self.binding_posts: list[tuple[str, str, str]] = []  # (ns, pod, node)
        self.annotation_patches: list[tuple[str, str, dict]] = []  # (ns, pod, ann)
        self.patch_conflicts_remaining = 0  # do_PATCH answers 409 while > 0
        self.pod_deletes: list[tuple[str, str]] = []  # (ns, pod)

    def apply(self, kind: str, etype: str, obj: dict) -> None:
        with self.cond:
            self.rv += 1
            obj = copy.deepcopy(obj)
            md = obj.setdefault("metadata", {})
            md["resourceVersion"] = str(self.rv)
            key = f"{md.get('namespace', '')}/{md['name']}"
            if etype == DELETED:
                self.objects[kind].pop(key, None)
            else:
                self.objects[kind][key] = obj
            self.events.append((self.rv, kind, etype, obj))
            self.cond.notify_all()

    def forget(self, kind: str, name: str, namespace: str = "") -> None:
        """Remove an object with NO event — simulates a change lost to
        compaction (only a relist can surface it)."""
        with self.cond:
            self.rv += 1
            self.objects[kind].pop(f"{namespace}/{name}", None)
            self.cond.notify_all()

    def compact(self) -> None:
        with self.cond:
            self.compacted = self.rv
            self.events.clear()
            self.cond.notify_all()

    def drop_watches(self) -> None:
        with self.cond:
            self.generation += 1
            self.cond.notify_all()


class _Handler(BaseHTTPRequestHandler):
    state: _ApiState  # set per-test

    def log_message(self, *a):  # quiet
        pass

    def do_GET(self) -> None:
        parsed = urllib.parse.urlparse(self.path)
        kind = _PATH_KINDS.get(parsed.path)
        if kind is None:
            m = re.match(r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)$", parsed.path)
            if m:
                with self.state.cond:
                    pod = self.state.objects["pods"].get(f"{m.group(1)}/{m.group(2)}")
                if pod is None:
                    self._send_json(404, {"kind": "Status", "code": 404})
                else:
                    self._send_json(200, pod)
                return
            self.send_error(404)
            return
        q = dict(urllib.parse.parse_qsl(parsed.query))
        if q.get("watch") == "1":
            self._serve_watch(kind, q)
        else:
            self._serve_list(kind)

    def _serve_list(self, kind: str) -> None:
        st = self.state
        with st.cond:
            body = json.dumps(
                {
                    "kind": "List",
                    "metadata": {"resourceVersion": str(st.rv)},
                    "items": list(st.objects[kind].values()),
                }
            ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_watch(self, kind: str, q: dict) -> None:
        st = self.state
        rv = int(q.get("resourceVersion", "0") or "0")
        deadline = time.monotonic() + min(float(q.get("timeoutSeconds", "30")), 30.0)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        with st.cond:
            if rv and rv < st.compacted:
                self._write_line(
                    {
                        "type": "ERROR",
                        "object": {"kind": "Status", "code": 410, "message": "too old resource version"},
                    }
                )
                return
            gen = st.generation
        while time.monotonic() < deadline:
            with st.cond:
                if st.generation != gen:
                    return
                pending = [e for e in st.events if e[0] > rv and e[1] == kind]
                if not pending:
                    st.cond.wait(timeout=0.1)
                    continue
            for erv, _k, etype, obj in pending:
                if not self._write_line({"type": etype, "object": obj}):
                    return
                rv = erv

    def _write_line(self, obj: dict) -> bool:
        try:
            self.wfile.write(json.dumps(obj).encode() + b"\n")
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError):
            return False

    # -- write verbs (the live write-back surface) --------------------------

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(n)) if n else {}

    def _send_json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self) -> None:
        m = re.match(r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)/binding$", self.path)
        if not m:
            self.send_error(404)
            return
        ns, name = m.group(1), m.group(2)
        body = self._read_body()
        node = ((body.get("target") or {}).get("name")) or ""
        st = self.state
        with st.cond:
            pod = st.objects["pods"].get(f"{ns}/{name}")
            if pod is None:
                self._send_json(404, {"kind": "Status", "code": 404})
                return
            if pod.get("spec", {}).get("nodeName"):
                # Real apiserver: "pod X is already assigned to node Y".
                self._send_json(409, {"kind": "Status", "code": 409})
                return
            st.binding_posts.append((ns, name, node))
        new = copy.deepcopy(pod)
        new.setdefault("spec", {})["nodeName"] = node
        st.apply("pods", MODIFIED, new)
        self._send_json(201, {"kind": "Status", "code": 201})

    def do_DELETE(self) -> None:
        m = re.match(r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)$", self.path)
        if not m:
            self.send_error(404)
            return
        ns, name = m.group(1), m.group(2)
        body = self._read_body()
        st = self.state
        with st.cond:
            pod = st.objects["pods"].get(f"{ns}/{name}")
            if pod is None:
                self._send_json(404, {"kind": "Status", "code": 404})
                return
            # DeleteOptions.preconditions.uid: the real apiserver answers
            # 409 Conflict when the live object's UID differs.
            want_uid = ((body or {}).get("preconditions") or {}).get("uid")
            if want_uid and want_uid != pod.get("metadata", {}).get("uid"):
                self._send_json(409, {"kind": "Status", "code": 409})
                return
            st.pod_deletes.append((ns, name))
        st.apply("pods", DELETED, pod)
        self._send_json(200, {"kind": "Status", "code": 200})

    def do_PATCH(self) -> None:
        m = re.match(r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)$", self.path)
        if not m or self.headers.get("Content-Type") != "application/merge-patch+json":
            self.send_error(404)
            return
        ns, name = m.group(1), m.group(2)
        patch = self._read_body()
        st = self.state
        with st.cond:
            pod = st.objects["pods"].get(f"{ns}/{name}")
            if pod is None:
                self._send_json(404, {"kind": "Status", "code": 404})
                return
            if st.patch_conflicts_remaining > 0:
                st.patch_conflicts_remaining -= 1
                self._send_json(409, {"kind": "Status", "code": 409})
                return
        ann = (patch.get("metadata") or {}).get("annotations") or {}
        new = copy.deepcopy(pod)
        merged = dict(new.setdefault("metadata", {}).get("annotations") or {})
        merged.update(ann)
        new["metadata"]["annotations"] = merged
        with st.cond:
            st.annotation_patches.append((ns, name, dict(ann)))
        st.apply("pods", MODIFIED, new)
        self._send_json(200, new)


@pytest.fixture()
def apiserver():
    state = _ApiState()
    handler = type("H", (_Handler,), {"state": state})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield state, f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        state.drop_watches()
        srv.shutdown()
        srv.server_close()


def _wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_list_and_snap_shape(apiserver):
    state, url = apiserver
    state.apply("nodes", ADDED, make_node("n0", cpu="4", memory="8Gi"))
    state.apply("pods", ADDED, make_pod("p0", cpu="1", memory="1Gi"))
    state.apply(
        "priorityclasses", ADDED, {"metadata": {"name": "system-node-critical"}, "value": 2000}
    )
    state.apply("namespaces", ADDED, {"metadata": {"name": "kube-system"}})
    state.apply("namespaces", ADDED, {"metadata": {"name": "apps"}})
    src = KubeApiSource(url)
    assert [o["metadata"]["name"] for o in src.list("nodes")] == ["n0"]
    snap = src.snap()
    assert {o["metadata"]["name"] for o in snap["nodes"]} == {"n0"}
    assert snap["pods"][0]["metadata"]["name"] == "p0"
    # System priority classes and kube- namespaces are excluded
    # (reference snapshot.go:586-599).
    assert snap["priorityClasses"] == []
    assert [o["metadata"]["name"] for o in snap["namespaces"]] == ["apps"]
    assert snap["schedulerConfig"] is None


def test_snap_label_selector(apiserver):
    state, url = apiserver
    state.apply("nodes", ADDED, make_node("keep", labels={"team": "a"}))
    state.apply("nodes", ADDED, make_node("drop", labels={"team": "b"}))
    snap = KubeApiSource(url).snap({"matchLabels": {"team": "a"}})
    assert [o["metadata"]["name"] for o in snap["nodes"]] == ["keep"]


def test_syncer_mirrors_live_apiserver(apiserver):
    state, url = apiserver
    state.apply("nodes", ADDED, make_node("n0", cpu="8", memory="16Gi"))
    pod = make_pod("p0", cpu="1", memory="1Gi")
    pod["metadata"]["uid"] = "src-uid-1"
    pod["metadata"]["ownerReferences"] = [{"kind": "ReplicaSet", "name": "rs"}]
    pod["spec"]["serviceAccountName"] = "robot"
    state.apply("pods", ADDED, pod)

    dest = ClusterStore()
    syncer = Syncer(KubeApiSource(url), dest)
    syncer.run()
    try:
        _wait_for(lambda: len(dest.list("pods")) == 1, msg="initial pod sync")
        synced = dest.list("pods")[0]
        # Mandatory mutators: source uid/ownerReferences/serviceAccount
        # stripped (reference syncer.go:174-181, resource.go:83-99).
        assert synced["metadata"]["uid"] != "src-uid-1"
        assert "ownerReferences" not in synced["metadata"]
        assert "serviceAccountName" not in synced["spec"]
        # The live UID survives out-of-band (eviction preconditions).
        from ksim_tpu.syncer.syncer import SOURCE_UID_ANNOTATION

        assert synced["metadata"]["annotations"][SOURCE_UID_ANNOTATION] == "src-uid-1"

        # Live create mirrors.
        state.apply("pods", ADDED, make_pod("p1", cpu="1", memory="1Gi"))
        _wait_for(lambda: len(dest.list("pods")) == 2, msg="live pod create")

        # Update to an unscheduled pod mirrors.
        p1 = copy.deepcopy(state.objects["pods"]["default/p1"])
        p1["metadata"]["labels"] = {"stage": "two"}
        state.apply("pods", MODIFIED, p1)
        _wait_for(
            lambda: dest.get("pods", "p1", "default")["metadata"].get("labels", {}).get("stage")
            == "two",
            msg="live pod update",
        )

        # Update to a SCHEDULED pod is filtered (resource.go:103-123): the
        # simulator's scheduler owns binding.
        dest.patch("pods", "p1", "default", lambda o: o["spec"].__setitem__("nodeName", "n0"))
        p1 = copy.deepcopy(state.objects["pods"]["default/p1"])
        p1["spec"]["nodeName"] = "src-node"
        p1["metadata"]["labels"] = {"stage": "three"}
        state.apply("pods", MODIFIED, p1)
        # Give the event time to flow, then confirm it did NOT apply.
        time.sleep(0.5)
        assert dest.get("pods", "p1", "default")["spec"]["nodeName"] == "n0"
        assert dest.get("pods", "p1", "default")["metadata"]["labels"]["stage"] == "two"

        # Deletes mirror.
        state.apply("pods", DELETED, {"metadata": {"name": "p0", "namespace": "default"}})
        _wait_for(
            lambda: all(o["metadata"]["name"] != "p0" for o in dest.list("pods")),
            msg="live pod delete",
        )
    finally:
        syncer.stop()


def test_watch_410_relist_converges(apiserver):
    """An etcd compaction during a watch gap still converges: the reader
    gets 410, relists, and synthesizes DELETED for vanished objects."""
    state, url = apiserver
    state.apply("nodes", ADDED, make_node("n0"))
    state.apply("nodes", ADDED, make_node("n1"))

    dest = ClusterStore()
    syncer = Syncer(KubeApiSource(url), dest, )
    syncer.run()
    try:
        _wait_for(lambda: len(dest.list("nodes")) == 2, msg="initial node sync")

        # n1 vanishes with no event (lost to compaction), history compacts,
        # and every active watch drops — the reconnect must take the
        # 410 -> relist path and emit the synthetic delete.
        state.forget("nodes", "n1")
        state.compact()
        state.drop_watches()
        _wait_for(
            lambda: [o["metadata"]["name"] for o in dest.list("nodes")] == ["n0"],
            msg="post-compaction relist delete",
        )
        # And new events after the relist still flow.
        state.apply("nodes", ADDED, make_node("n2"))
        _wait_for(lambda: len(dest.list("nodes")) == 2, msg="post-relist create")
    finally:
        syncer.stop()


# -- kubeconfig parsing ------------------------------------------------------


def _write_kubeconfig(tmp_path, user: dict, cluster: dict | None = None) -> str:
    cfg = {
        "apiVersion": "v1",
        "kind": "Config",
        "current-context": "ctx",
        "contexts": [{"name": "ctx", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": cluster or {"server": "http://127.0.0.1:8080"}}],
        "users": [{"name": "u", "user": user}],
    }
    p = tmp_path / "kubeconfig.yaml"
    import yaml

    p.write_text(yaml.safe_dump(cfg))
    return str(p)


def test_kubeconfig_bearer_token(tmp_path):
    p = _write_kubeconfig(tmp_path, {"token": "sekret"})
    cc = load_kubeconfig(p)
    assert cc["server"] == "http://127.0.0.1:8080"
    assert cc["headers"]["Authorization"] == "Bearer sekret"
    assert cc["ssl_context"] is None  # plain http


def test_kubeconfig_basic_auth_and_insecure_tls(tmp_path):
    p = _write_kubeconfig(
        tmp_path,
        {"username": "admin", "password": "pw"},
        {"server": "https://10.0.0.1:6443", "insecure-skip-tls-verify": True},
    )
    cc = load_kubeconfig(p)
    expected = "Basic " + base64.b64encode(b"admin:pw").decode()
    assert cc["headers"]["Authorization"] == expected
    assert cc["ssl_context"] is not None
    assert cc["ssl_context"].check_hostname is False


def test_kubeconfig_token_file(tmp_path):
    tok = tmp_path / "token"
    tok.write_text("from-file\n")
    p = _write_kubeconfig(tmp_path, {"tokenFile": str(tok)})
    assert load_kubeconfig(p)["headers"]["Authorization"] == "Bearer from-file"


def test_kubeconfig_rejects_exec_and_missing_context(tmp_path):
    p = _write_kubeconfig(tmp_path, {"exec": {"command": "aws"}})
    with pytest.raises(InvalidConfigError, match="KSIM_ALLOW_EXEC_CREDENTIALS"):
        load_kubeconfig(p)
    with pytest.raises(InvalidConfigError, match="context"):
        load_kubeconfig(p, context="nope")
    with pytest.raises(InvalidConfigError):
        load_kubeconfig(str(tmp_path / "missing.yaml"))


def _stub_exec_plugin(tmp_path, body: str) -> str:
    """A stub credential plugin script (the shape GKE's
    gke-gcloud-auth-plugin / EKS's aws eks get-token emit)."""
    import stat

    script = tmp_path / "cred-plugin.py"
    script.write_text("#!/usr/bin/env python3\n" + body)
    script.chmod(script.stat().st_mode | stat.S_IXUSR)
    return str(script)


def test_kubeconfig_exec_plugin_token(tmp_path, monkeypatch):
    """Gated exec credential plugins (client-go ExecCredential protocol):
    the plugin's status.token becomes the bearer header, and the plugin
    sees KUBERNETES_EXEC_INFO."""
    monkeypatch.setenv("KSIM_ALLOW_EXEC_CREDENTIALS", "1")
    script = _stub_exec_plugin(
        tmp_path,
        "import json, os, sys\n"
        "info = json.loads(os.environ['KUBERNETES_EXEC_INFO'])\n"
        "assert info['kind'] == 'ExecCredential'\n"
        "assert os.environ.get('PLUGIN_FLAVOR') == 'stub'\n"
        "assert sys.argv[1:] == ['get-token']\n"
        "print(json.dumps({'apiVersion': info['apiVersion'],"
        " 'kind': 'ExecCredential',"
        " 'status': {'token': 'exec-token'}}))\n",
    )
    p = _write_kubeconfig(
        tmp_path,
        {
            "exec": {
                "apiVersion": "client.authentication.k8s.io/v1beta1",
                "command": sys.executable,
                "args": [script, "get-token"],
                "env": [{"name": "PLUGIN_FLAVOR", "value": "stub"}],
            }
        },
    )
    cc = load_kubeconfig(p)
    assert cc["headers"]["Authorization"] == "Bearer exec-token"


def test_kubeconfig_exec_plugin_failure_is_config_error(tmp_path, monkeypatch):
    monkeypatch.setenv("KSIM_ALLOW_EXEC_CREDENTIALS", "1")
    failing = _stub_exec_plugin(
        tmp_path, "import sys\nsys.stderr.write('no creds')\nsys.exit(3)\n"
    )
    p = _write_kubeconfig(
        tmp_path,
        {"exec": {"command": sys.executable, "args": [failing]}},
    )
    with pytest.raises(InvalidConfigError, match="exited 3"):
        load_kubeconfig(p)
    # Empty status is an error too — auth must fail loudly.
    sub = tmp_path / "e"
    sub.mkdir()
    empty = _stub_exec_plugin(sub, "print('{\"status\": {}}')\n")
    p2 = _write_kubeconfig(
        tmp_path, {"exec": {"command": sys.executable, "args": [empty]}}
    )
    with pytest.raises(InvalidConfigError, match="no credentials"):
        load_kubeconfig(p2)


def test_kubeapi_source_from_kubeconfig_lists(apiserver, tmp_path):
    state, url = apiserver
    state.apply("nodes", ADDED, make_node("n0"))
    p = _write_kubeconfig(tmp_path, {"token": "t"}, {"server": url})
    src = KubeApiSource.from_kubeconfig(p)
    assert [o["metadata"]["name"] for o in src.list("nodes")] == ["n0"]
    src.close()


def test_syncer_survives_apiserver_outage():
    """The watch readers reconnect with backoff through a full apiserver
    outage (connection refused), and changes made while reconnecting
    arrive once the server returns."""
    state = _ApiState()
    handler = type("H", (_Handler,), {"state": state})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    srv.daemon_threads = True
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    state.apply("nodes", ADDED, make_node("n0"))

    dest = ClusterStore()
    syncer = Syncer(KubeApiSource(f"http://127.0.0.1:{port}"), dest)
    syncer.run()
    try:
        _wait_for(lambda: len(dest.list("nodes")) == 1, msg="initial sync")

        # Outage: kill the server; readers hit connection-refused and
        # back off.
        state.drop_watches()
        srv.shutdown()
        srv.server_close()
        time.sleep(1.5)  # a few reconnect attempts against a dead port

        # Server returns on the SAME port with new state added meanwhile.
        # (Short retry: another process could grab the freed port.)
        state.apply("nodes", ADDED, make_node("n1"))
        srv2 = None
        for _ in range(50):
            try:
                srv2 = ThreadingHTTPServer(("127.0.0.1", port), handler)
                break
            except OSError:
                time.sleep(0.1)
        assert srv2 is not None, "could not rebind the outage port"
        srv2.daemon_threads = True
        threading.Thread(target=srv2.serve_forever, daemon=True).start()
        try:
            _wait_for(
                lambda: len(dest.list("nodes")) == 2, timeout=20,
                msg="post-outage convergence",
            )
        finally:
            state.drop_watches()
            srv2.shutdown()
            srv2.server_close()
    finally:
        syncer.stop()


def test_exec_credentials_refresh_near_expiry(apiserver):
    """An exec token past its expirationTimestamp re-runs the plugin
    before the next request (client-go credential rotation; EKS tokens
    live ~15 min while the syncer runs indefinitely)."""
    _state, url = apiserver
    calls = []

    def refresh():
        calls.append(1)
        return {"Authorization": f"Bearer fresh-{len(calls)}"}, time.time() + 3600

    src = KubeApiSource(
        url,
        headers={"Authorization": "Bearer stale"},
        headers_expiry=time.time() - 10,
        headers_refresh=refresh,
    )
    src.list("nodes")
    assert calls == [1]
    assert src._headers["Authorization"] == "Bearer fresh-1"
    # Fresh expiry far in the future: no re-exec on the next request.
    src.list("nodes")
    assert calls == [1]


def test_kubeconfig_exec_expiry_parsed(tmp_path, monkeypatch):
    monkeypatch.setenv("KSIM_ALLOW_EXEC_CREDENTIALS", "1")
    script = _stub_exec_plugin(
        tmp_path,
        "import json\n"
        "print(json.dumps({'kind': 'ExecCredential', 'status': {\n"
        "  'token': 'tok',\n"
        "  'expirationTimestamp': '2099-01-01T00:00:00Z'}}))\n",
    )
    p = _write_kubeconfig(
        tmp_path, {"exec": {"command": sys.executable, "args": [script]}}
    )
    cc = load_kubeconfig(p)
    assert cc["headers"]["Authorization"] == "Bearer tok"
    assert cc["headers_expiry"] > time.time()
    # The refresh closure re-runs the plugin and returns fresh headers.
    fresh, expiry = cc["headers_refresh"]()
    assert fresh == {"Authorization": "Bearer tok"}
    assert expiry > time.time()


def test_live_writeback_round_trip(apiserver):
    """The round-5 verdict's acceptance test: a pod created on the (stub)
    apiserver is scheduled by the engine and the stub then holds the BIND
    (via the binding subresource) plus the recorded result annotations —
    the reference's debuggable-scheduler-on-a-real-cluster flow
    (debuggable_scheduler.go:157-173, storereflector.go:78-146)."""
    from ksim_tpu.engine.annotations import ALL_RESULT_KEYS
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.syncer.writeback import LiveWriteBack

    state, url = apiserver
    state.apply("nodes", ADDED, make_node("n0", cpu="8", memory="16Gi"))
    state.apply("pods", ADDED, make_pod("live-pod", cpu="1", memory="1Gi"))

    src = KubeApiSource(url)
    store = ClusterStore()
    syncer = Syncer(src, store)
    syncer.run()
    wb = LiveWriteBack(src, store).start()
    try:
        _wait_for(
            lambda: len(store.list("pods")) == 1 and len(store.list("nodes")) == 1,
            msg="mirror sync",
        )
        svc = SchedulerService(store, record="full", preemption=False)
        placements = svc.schedule_pending()
        assert placements == {"default/live-pod": "n0"}

        def bound_live():
            pod = state.objects["pods"].get("default/live-pod")
            return bool(pod and pod.get("spec", {}).get("nodeName") == "n0")

        _wait_for(bound_live, msg="live bind")
        assert ("default", "live-pod", "n0") in state.binding_posts

        def annotated_live():
            pod = state.objects["pods"].get("default/live-pod")
            ann = (pod or {}).get("metadata", {}).get("annotations") or {}
            return all(k in ann for k in ALL_RESULT_KEYS)

        _wait_for(annotated_live, msg="live result annotations")
        pod = state.objects["pods"]["default/live-pod"]
        ann = pod["metadata"]["annotations"]
        assert ann["kube-scheduler-simulator.sigs.k8s.io/selected-node"] == "n0"
        # Unschedulable pods get annotation-only write-back (no bind).
        state.apply(
            "pods", ADDED, make_pod("too-big", cpu="100", memory="1Ti")
        )
        _wait_for(
            lambda: any(
                namespace_name == ("default", "too-big")
                for namespace_name in (
                    (ns, n) for ns, n, _ in state.annotation_patches
                )
            ) or len(store.list("pods")) == 2,
            msg="second pod mirrored",
        )
        svc.schedule_pending()
        _wait_for(
            lambda: "kube-scheduler-simulator.sigs.k8s.io/filter-result"
            in (
                (state.objects["pods"].get("default/too-big") or {})
                .get("metadata", {})
                .get("annotations")
                or {}
            ),
            msg="unschedulable annotations",
        )
        assert not state.objects["pods"]["default/too-big"]["spec"].get("nodeName")
    finally:
        wb.stop()
        syncer.stop()
        src.close()


def test_bind_pod_conflict_and_patch_retry(apiserver):
    """Direct write-verb semantics: binding an already-bound pod answers
    409 (KubeApiError.code), and patching a missing pod answers 404."""
    from ksim_tpu.syncer.kubeapi import KubeApiError

    state, url = apiserver
    bound = make_pod("pinned", cpu="1", memory="1Gi", node_name="n9")
    state.apply("pods", ADDED, bound)
    src = KubeApiSource(url)
    with pytest.raises(KubeApiError) as e:
        src.bind_pod("default", "pinned", "n0")
    assert e.value.code == 409
    with pytest.raises(KubeApiError) as e:
        src.patch_pod_annotations("default", "nope", {"a/b": "c"})
    assert e.value.code == 404
    # Happy-path patch merges without clobbering existing annotations.
    src.patch_pod_annotations("default", "pinned", {"x.io/k": "v"})
    ann = state.objects["pods"]["default/pinned"]["metadata"]["annotations"]
    assert ann["x.io/k"] == "v"


def test_patch_retry_survives_conflicts_then_exhausts(apiserver):
    """The 409 bounded-retry loop in patch_pod_annotations: conflicts
    below the attempt budget succeed after retrying; a persistently
    conflicting object exhausts the budget and raises code 409."""
    from ksim_tpu.syncer.kubeapi import KubeApiError

    state, url = apiserver
    state.apply("pods", ADDED, make_pod("busy", cpu="1", memory="1Gi"))
    src = KubeApiSource(url)
    state.patch_conflicts_remaining = 2
    src.patch_pod_annotations("default", "busy", {"x.io/k": "v1"})  # retries through
    assert state.patch_conflicts_remaining == 0
    ann = state.objects["pods"]["default/busy"]["metadata"]["annotations"]
    assert ann["x.io/k"] == "v1"
    state.patch_conflicts_remaining = 99
    with pytest.raises(KubeApiError) as e:
        src.patch_pod_annotations("default", "busy", {"x.io/k": "v2"})
    assert e.value.code == 409
    assert state.patch_conflicts_remaining == 99 - 4  # attempts budget


def test_writeback_evicts_only_noted_preemption_victims(apiserver):
    """Live deletes carry eviction provenance: a store delete marked via
    note_eviction (what SchedulerService.add_eviction_listener feeds)
    deletes the live pod, while a plain store delete (reset, user delete
    through the simulator API) must NEVER touch the real cluster
    (review findings, round 5)."""
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.syncer.writeback import LiveWriteBack

    state, url = apiserver
    state.apply("nodes", ADDED, make_node("n0", cpu="8", memory="16Gi"))
    state.apply("pods", ADDED, make_pod("victim", cpu="1", memory="1Gi"))
    state.apply("pods", ADDED, make_pod("innocent", cpu="1", memory="1Gi"))
    src = KubeApiSource(url)
    store = ClusterStore()
    syncer = Syncer(src, store)
    syncer.run()
    wb = LiveWriteBack(src, store).start()
    try:
        _wait_for(lambda: len(store.list("pods")) == 2, msg="mirror")
        svc = SchedulerService(store, record="selection", preemption=False)
        svc.add_eviction_listener(wb.note_eviction)
        placements = svc.schedule_pending()
        assert placements["default/victim"] == "n0"
        _wait_for(
            lambda: ("default", "victim", "n0") in state.binding_posts,
            msg="live bind",
        )
        # Plain store delete (user/reset): live cluster untouched.
        store.delete("pods", "innocent", "default")
        time.sleep(0.5)
        assert ("default", "innocent") not in state.pod_deletes
        assert "default/innocent" in state.objects["pods"]
        # Eviction-marked delete (what _evict_victim does): propagates.
        wb.note_eviction("default", "victim")
        store.delete("pods", "victim", "default")
        _wait_for(
            lambda: ("default", "victim") in state.pod_deletes,
            msg="live eviction",
        )
        assert "default/victim" not in state.objects["pods"]
    finally:
        wb.stop()
        syncer.stop()
        src.close()


def test_service_eviction_listener_fires_on_preemption_path():
    """_evict_victim notifies listeners before the store delete — the
    provenance hook cmd/simulator wires into LiveWriteBack."""
    from ksim_tpu.scheduler.service import SchedulerService

    store = ClusterStore()
    store.create("pods", make_pod("v1", cpu="1", memory="1Gi", node_name="nX"))
    svc = SchedulerService(store, record="selection", preemption=False)
    seen: list[tuple[str, str]] = []
    svc.add_eviction_listener(lambda ns, n: seen.append((ns, n)))
    svc._evict_victim(store.get("pods", "v1", "default"))
    assert seen == [("default", "v1")]
    with pytest.raises(Exception):
        store.get("pods", "v1", "default")


def test_writeback_409_reconciles_to_real_node(apiserver):
    """If another scheduler bound the pod first (bind answers 409), the
    write-back must NOT push result annotations naming OUR node — it
    re-reads the live pod and skips when the real node differs (review
    finding, round 5)."""
    from ksim_tpu.syncer.writeback import LiveWriteBack

    state, url = apiserver
    # Live pod is ALREADY bound to n3 (by "another scheduler").
    state.apply("pods", ADDED, make_pod("contested", cpu="1", memory="1Gi",
                                        node_name="n3"))
    src = KubeApiSource(url)
    store = ClusterStore()
    # Mirror it UNBOUND (as the syncer would have before the other
    # scheduler's bind, which the filter then never mirrors).
    store.create("pods", make_pod("contested", cpu="1", memory="1Gi"))
    wb = LiveWriteBack(src, store).start()
    try:
        time.sleep(0.3)  # let the ADDED replay seed (no writes expected)
        # Our scheduler now "places" it on n0 with result annotations.
        def bindit(obj):
            obj["spec"]["nodeName"] = "n0"
            obj["metadata"].setdefault("annotations", {})[
                "kube-scheduler-simulator.sigs.k8s.io/selected-node"
            ] = "n0"
        store.patch("pods", "contested", "default", bindit)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not wb._bound.get("default/contested"):
            time.sleep(0.05)
        assert wb._bound.get("default/contested") == "n3"  # learned the truth
        live = state.objects["pods"]["default/contested"]
        ann = live.get("metadata", {}).get("annotations") or {}
        assert "kube-scheduler-simulator.sigs.k8s.io/selected-node" not in ann
        assert live["spec"]["nodeName"] == "n3"
        assert state.annotation_patches == []
        # A later MODIFIED for the diverged pod must not re-attempt the
        # guaranteed-409 bind or push annotations (review finding).
        assert "default/contested" in wb._diverged
        store.patch(
            "pods", "contested", "default",
            lambda o: o["metadata"]["annotations"].__setitem__(
                "kube-scheduler-simulator.sigs.k8s.io/selected-node", "n0"
            ),
        )
        time.sleep(0.5)
        assert state.annotation_patches == []
    finally:
        wb.stop()
        src.close()


def test_writeback_stop_drains_pending_eviction_recheck(apiserver, monkeypatch):
    """stop() must not strand a marked eviction parked in the DELETED
    recheck window — the exit drain completes the live delete (review
    finding, round 5).  Deterministic sequencing: an UNMARKED delete
    always parks once (the attempt-0 recheck), and the recheck delay is
    raised far beyond the test's runtime so the worker provably cannot
    consume the parked entry before stop() — only the drain can have
    performed the eviction."""
    from ksim_tpu.syncer.writeback import LiveWriteBack

    monkeypatch.setattr(LiveWriteBack, "RECHECK_DELAY_S", 30.0)
    state, url = apiserver
    state.apply("pods", ADDED, make_pod("victim", cpu="1", memory="1Gi",
                                        node_name="n0"))
    src = KubeApiSource(url)
    store = ClusterStore()
    store.create("pods", make_pod("victim", cpu="1", memory="1Gi",
                                  node_name="n0"))
    wb = LiveWriteBack(src, store).start()
    try:
        store.delete("pods", "victim", "default")
        # The DELETED event (unmarked) parks in the recheck window.
        _wait_for(lambda: wb._retries, msg="recheck parked")
        wb.note_eviction("default", "victim")
        # Mark is set, so the drain takes no grace sleep; RECHECK_DELAY
        # only gates UNMARKED work there.
        wb.stop()  # drain must run the parked eviction
        _wait_for(
            lambda: ("default", "victim") in state.pod_deletes,
            timeout=5.0,
            msg="drained live eviction",
        )
    finally:
        wb.stop()
        src.close()


def test_delete_pod_uid_precondition(apiserver):
    """delete_pod ships DeleteOptions.preconditions.uid: a stale UID
    answers 409 and the live pod survives (the same-name-recreation
    window the reference guards, storereflector.go:94-96)."""
    from ksim_tpu.syncer.kubeapi import KubeApiError

    state, url = apiserver
    pod = make_pod("guarded", cpu="1", memory="1Gi")
    pod["metadata"]["uid"] = "uid-live"
    state.apply("pods", ADDED, pod)
    src = KubeApiSource(url)
    with pytest.raises(KubeApiError) as e:
        src.delete_pod("default", "guarded", uid="uid-stale")
    assert e.value.code == 409
    assert "default/guarded" in state.objects["pods"]
    # Matching UID (and the no-precondition legacy form) both delete.
    src.delete_pod("default", "guarded", uid="uid-live")
    assert "default/guarded" not in state.objects["pods"]


def test_writeback_eviction_spares_recreated_same_name_pod(apiserver):
    """An eviction whose victim was deleted AND recreated live (same
    name, new UID) must leave the new pod alone: the store event's UID
    rides as the delete precondition and the 409 is treated as settled."""
    from ksim_tpu.syncer.writeback import LiveWriteBack

    from ksim_tpu.syncer.syncer import SOURCE_UID_ANNOTATION

    state, url = apiserver
    src = KubeApiSource(url)
    store = ClusterStore()
    victim = make_pod("reborn", cpu="1", memory="1Gi", node_name="n0")
    # The mirrored pod remembers its live UID (what the syncer records).
    victim["metadata"]["annotations"] = {SOURCE_UID_ANNOTATION: "uid-old-life"}
    store.create("pods", victim)
    # Live cluster: the same name already belongs to a RECREATED pod.
    live = make_pod("reborn", cpu="1", memory="1Gi")
    live["metadata"]["uid"] = "uid-new-life"
    state.apply("pods", ADDED, live)
    wb = LiveWriteBack(src, store).start()
    try:
        wb.note_eviction("default", "reborn")
        store.delete("pods", "reborn", "default")
        _wait_for(
            lambda: "default/reborn" not in wb._evictions,
            msg="eviction settled",
        )
        # The recreated live pod survived; no delete was recorded.
        assert "default/reborn" in state.objects["pods"]
        assert ("default", "reborn") not in state.pod_deletes
    finally:
        wb.stop()
        src.close()


def test_writeback_409_reconcile_checks_uid(apiserver):
    """The bind-409 reconcile GET compares UIDs before annotation
    patches: a same-name recreated live pod (different UID) must not
    receive our result annotations even if its node happens to match."""
    from ksim_tpu.syncer.writeback import LiveWriteBack

    from ksim_tpu.syncer.syncer import SOURCE_UID_ANNOTATION

    state, url = apiserver
    live = make_pod("swapped", cpu="1", memory="1Gi", node_name="n0")
    live["metadata"]["uid"] = "uid-live"
    state.apply("pods", ADDED, live)
    src = KubeApiSource(url)
    store = ClusterStore()
    ours = make_pod("swapped", cpu="1", memory="1Gi")
    ours["metadata"]["annotations"] = {SOURCE_UID_ANNOTATION: "uid-ours"}
    store.create("pods", ours)
    wb = LiveWriteBack(src, store).start()
    try:
        time.sleep(0.3)  # ADDED replay seeds caches

        def bindit(obj):
            obj["spec"]["nodeName"] = "n0"  # same node as the live pod
            obj["metadata"].setdefault("annotations", {})[
                "kube-scheduler-simulator.sigs.k8s.io/selected-node"
            ] = "n0"

        store.patch("pods", "swapped", "default", bindit)
        _wait_for(
            lambda: "default/swapped" in wb._diverged, msg="uid divergence"
        )
        assert state.annotation_patches == []
    finally:
        wb.stop()
        src.close()


def test_writeback_annotation_dedupe_by_equality(apiserver):
    """The last-pushed annotation cache stores the sorted item tuple and
    compares by EQUALITY (a hash fingerprint could collide and silently
    skip a push): identical re-pushes dedupe, changed sets push."""
    from ksim_tpu.syncer.writeback import LiveWriteBack

    state, url = apiserver
    state.apply("pods", ADDED, make_pod("annotated", cpu="1", memory="1Gi"))
    src = KubeApiSource(url)
    store = ClusterStore()
    store.create("pods", make_pod("annotated", cpu="1", memory="1Gi"))
    wb = LiveWriteBack(src, store).start()
    try:
        time.sleep(0.3)
        ann_key = "kube-scheduler-simulator.sigs.k8s.io/filter-result"

        def annotate(value):
            def mut(obj):
                obj["metadata"].setdefault("annotations", {})[ann_key] = value
            store.patch("pods", "annotated", "default", mut)

        annotate("v1")
        _wait_for(lambda: len(state.annotation_patches) == 1, msg="first push")
        assert wb._pushed["default/annotated"] == ((ann_key, "v1"),)
        # Touch the pod without changing the annotation set: no new push.
        store.patch("pods", "annotated", "default", lambda obj: None)
        time.sleep(0.4)
        assert len(state.annotation_patches) == 1
        annotate("v2")
        _wait_for(lambda: len(state.annotation_patches) == 2, msg="changed push")
    finally:
        wb.stop()
        src.close()


def test_writeback_exit_drain_warns_about_dropped_updates(apiserver, caplog):
    """Exit must enumerate the non-DELETED work it drops (queued MODIFIED
    events, pending retries): silent loss here IS store/live divergence,
    and the operator gets no other signal."""
    import logging as _logging

    from ksim_tpu.syncer.writeback import LiveWriteBack

    state, url = apiserver
    src = KubeApiSource(url)
    store = ClusterStore()
    store.create("pods", make_pod("lost", cpu="1", memory="1Gi"))
    wb = LiveWriteBack(src, store)
    # Never start the worker: enqueue a MODIFIED through the stream and a
    # pending retry by hand, then run the drain path directly via _run
    # with stop already set (the loop exits immediately into finally).
    wb._stream = store.watch(("pods",))
    store.patch(
        "pods", "lost", "default",
        lambda obj: obj["metadata"].setdefault("annotations", {}).update(
            {"kube-scheduler-simulator.sigs.k8s.io/filter-result": "x"}
        ),
    )
    wb._retries.append((0.0, "MODIFIED", store.get("pods", "lost", "default"), 1))
    wb._stop.set()
    with caplog.at_level(_logging.WARNING, logger="ksim_tpu.syncer.writeback"):
        wb._run()
    msgs = [r.getMessage() for r in caplog.records]
    assert any(
        "undelivered non-eviction" in m and "default/lost" in m for m in msgs
    )
    src.close()
