"""Out-of-tree samples (NodeNumber, data provider) and PluginExtender
Before/After hooks — the reference's extension surface
(pkg/debuggablescheduler WithPlugin/WithPluginExtenders,
wrappedplugin.go:47-171)."""

from __future__ import annotations

import json

import pytest

import jax.numpy as jnp
import numpy as np

from ksim_tpu.engine import Engine
from ksim_tpu.engine.annotations import SCORE_RESULT_KEY
from ksim_tpu.engine.core import PluginExtender, ScoredPlugin
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins.base import FilterOutput
from ksim_tpu.plugins.samples import (
    data_provider_builder,
    encode_node_number,
    node_number_builder,
    provider_encoder,
)
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod


def test_node_number_scores_suffix_match():
    nodes = [make_node("node-1"), make_node("node-2"), make_node("nodigit")]
    queue = [make_pod("pod-2"), make_pod("pod-x")]
    feats = Featurizer(
        extra_encoders={"nodenumber": encode_node_number}
    ).featurize(nodes, [], queue_pods=queue)
    build = node_number_builder()
    sp = build(feats, {})
    eng = Engine(feats, (*default_plugins(feats), sp), record="full")
    res = eng.evaluate_batch()
    si = res.plugin_names.index("NodeNumber")
    # pod-2 matches node-2 only; pod-x (no digit) scores 0 everywhere.
    assert [int(x) for x in res.scores[0, si, :3]] == [0, 10, 0]
    assert [int(x) for x in res.scores[1, si, :3]] == [0, 0, 0]
    # reverse=True flips it.
    sp_rev = node_number_builder(reverse=True)(feats, {})
    eng2 = Engine(feats, (*default_plugins(feats), sp_rev), record="full")
    res2 = eng2.evaluate_batch()
    si2 = res2.plugin_names.index("NodeNumber")
    assert [int(x) for x in res2.scores[0, si2, :3]] == [10, 0, 10]


def test_node_number_through_service_registry():
    """Full out-of-tree flow: registry Builder + featurizer extra encoder
    + profile enabling the plugin at the score point."""
    store = ClusterStore()
    store.create("nodes", make_node("big-5", cpu="64", memory="128Gi"))
    store.create("nodes", make_node("node-7", cpu="64", memory="128Gi"))
    store.create("pods", make_pod("app-7", cpu="100m"))
    cfg = {
        "profiles": [{
            "schedulerName": "default-scheduler",
            "plugins": {"multiPoint": {"enabled": [
                {"name": "NodeNumber", "weight": 100}  # dominate ties
            ]}},
        }]
    }
    svc = SchedulerService(
        store,
        config=cfg,
        registry={"NodeNumber": node_number_builder()},
        featurizer=Featurizer(extra_encoders={"nodenumber": encode_node_number}),
    )
    assert svc.schedule_pending() == {"default/app-7": "node-7"}
    anno = store.get("pods", "app-7")["metadata"]["annotations"]
    scores = json.loads(anno[SCORE_RESULT_KEY])
    assert scores["node-7"]["NodeNumber"] == "10"


def test_data_provider_capability():
    """The fork's external-data scorer as a capability: provider runs
    host-side at featurize time, never in the scoring hot path."""
    calls = []

    def provider(nodes):
        calls.append(len(nodes))
        return np.asarray([90 if "green" in n["metadata"]["name"] else 5
                           for n in nodes])

    store = ClusterStore()
    store.create("nodes", make_node("dirty-dc", cpu="64", memory="128Gi"))
    store.create("nodes", make_node("green-dc", cpu="64", memory="128Gi"))
    store.create("pods", make_pod("p", cpu="100m"))
    svc = SchedulerService(
        store,
        config={"profiles": [{
            "plugins": {"multiPoint": {"enabled": [
                {"name": "Renewable", "weight": 10}]}},
        }]},
        registry={"Renewable": data_provider_builder("Renewable", provider)},
        featurizer=Featurizer(
            extra_encoders={"provider:Renewable": provider_encoder(provider)}
        ),
    )
    assert svc.schedule_pending() == {"default/p": "green-dc"}
    assert calls  # the provider ran (once per featurization)


def test_plugin_extender_hooks():
    """Before/After hooks compile into the engine programs."""
    nodes = [make_node("a"), make_node("b")]
    queue = [make_pod("p")]
    feats = Featurizer().featurize(nodes, [], queue_pods=queue)
    base = default_plugins(feats)

    seen = {}

    def after_filter(state, pod, aux, out: FilterOutput) -> FilterOutput:
        seen["filter"] = True
        # Veto node 0 regardless of the plugin's verdict.
        n = out.ok.shape[0]
        veto = jnp.arange(n) == 0
        return FilterOutput(
            ok=out.ok & ~veto,
            reason_bits=jnp.where(veto, 1, out.reason_bits).astype(jnp.int32),
        )

    def after_score(state, pod, aux, scores):
        seen["score"] = True
        return scores + 7

    wrapped = tuple(
        ScoredPlugin(
            sp.plugin, sp.weight, sp.filter_enabled, sp.score_enabled,
            extender=PluginExtender(after_filter=after_filter, after_score=after_score)
            if sp.plugin.name == "NodeResourcesFit"
            else None,
        )
        for sp in base
    )
    eng = Engine(feats, wrapped, record="full")
    res = eng.evaluate_batch()
    assert seen == {"filter": True, "score": True}
    fi = res.filter_plugin_names.index("NodeResourcesFit")
    assert int(res.reason_bits[0, fi, 0]) == 1  # vetoed by the hook
    assert int(res.selected[0]) == 1
    # after_score applied pre-normalize: raw scores shifted by exactly 7.
    plain = Engine(feats, base, record="full").evaluate_batch()
    si = res.plugin_names.index("NodeResourcesFit")
    assert int(res.scores[0, si, 1]) == int(plain.scores[0, si, 1]) + 7


def test_config_loaded_plugin_via_builder_import():
    """Out-of-tree plugin enabled purely from configuration — the
    reference's wasm-plugin loading capability (RegisterWasmPlugins,
    scheduler/config/wasm.go:14-58): no registry or featurizer is passed
    in code; pluginConfig's builderImport names the plugin package."""
    store = ClusterStore()
    store.create("nodes", make_node("big-5", cpu="64", memory="128Gi"))
    store.create("nodes", make_node("node-7", cpu="64", memory="128Gi"))
    store.create("pods", make_pod("app-7", cpu="100m"))
    cfg = {
        "profiles": [{
            "schedulerName": "default-scheduler",
            "plugins": {"multiPoint": {"enabled": [
                {"name": "NodeNumber", "weight": 100}
            ]}},
            "pluginConfig": [{
                "name": "NodeNumber",
                "args": {"builderImport":
                         "ksim_tpu.plugins.samples.nodenumber:NODE_NUMBER_PLUGIN"},
            }],
        }]
    }
    svc = SchedulerService(store, config=cfg)
    assert svc.schedule_pending() == {"default/app-7": "node-7"}


def test_builder_import_errors():
    from ksim_tpu.scheduler.profile import load_plugin_import

    with pytest.raises(ValueError, match="must look like"):
        load_plugin_import("no-colon")
    with pytest.raises(ValueError, match="cannot load"):
        load_plugin_import("ksim_tpu.nope:thing")
    with pytest.raises(ValueError, match="cannot load"):
        load_plugin_import("ksim_tpu.plugins.samples.nodenumber:missing_attr")
    with pytest.raises(ValueError, match="callable builder"):
        load_plugin_import("ksim_tpu.plugins.samples.nodenumber:__doc__")


def test_builder_import_untrusted_config_rejected():
    """builderImport executes arbitrary imports, so runtime-applied
    configs (HTTP POST, snapshot import) are rejected unless the operator
    opted in; the boot config is operator-owned and trusted."""
    store = ClusterStore()
    svc = SchedulerService(store)
    cfg = {
        "profiles": [{
            "pluginConfig": [{
                "name": "NodeNumber",
                "args": {"builderImport":
                         "ksim_tpu.plugins.samples.nodenumber:NODE_NUMBER_PLUGIN"},
            }],
            "plugins": {"multiPoint": {"enabled": [{"name": "NodeNumber"}]}},
        }]
    }
    with pytest.raises(ValueError, match="not trusted"):
        svc.apply_scheduler_config(cfg)
    # Opt-in service accepts the same config at runtime.
    svc2 = SchedulerService(store, allow_plugin_imports=True)
    svc2.apply_scheduler_config(cfg)
