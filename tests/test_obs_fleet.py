"""Fleet observability plane (round 21, ksim_tpu/obs.py fleet section
+ jobs/fleet.py publisher): exact bucket-wise histogram merging, the
Prometheus exposition renderer/parser pair, crash-atomic per-worker
snapshot publishing, frontdoor aggregation with staleness flags, and
merged Chrome traces with cross-process flow events.

The 2-process fleet smoke (slow-marked) is the `make obs-check` leg:
counter sums across the merged document equal the per-worker sums, and
a SIGKILLed worker surfaces as ``stale_s > 0`` — never silently
dropped (docs/observability.md "Fleet observability")."""

from __future__ import annotations

import http.client
import json
import os
import random
import subprocess
import sys
import time

import pytest

from ksim_tpu import obs
from ksim_tpu.obs import (
    LatencyHistogram,
    merge_chrome_traces,
    merge_fleet_docs,
    merge_latency_snapshots,
    parse_prometheus,
    publish_snapshot,
    render_prometheus,
)
from tests.helpers import make_node, make_pod, sanitized_cpu_env


# ---------------------------------------------------------------------------
# Histogram merging: exact by construction (fixed edges)
# ---------------------------------------------------------------------------


def test_histogram_merge_property():
    """Bucket-wise merge of K snapshots == the histogram of the
    concatenated observations — exact because every LatencyHistogram
    shares the same 33 fixed log-spaced edges.  Randomized but seeded:
    observations span below-first-edge, mid-range and overflow."""
    rng = random.Random(1234)
    for _ in range(20):
        k = rng.randint(1, 6)
        parts, union = [], LatencyHistogram()
        for _ in range(k):
            h = LatencyHistogram()
            for _ in range(rng.randint(0, 40)):
                v = 10 ** rng.uniform(-7.5, 2.5)  # spans edges + overflow
                h.observe(v)
                union.observe(v)
            parts.append(h.snapshot())
        merged = merge_latency_snapshots(parts)
        want = union.snapshot()
        assert merged["count"] == want["count"]
        assert merged["buckets"] == want["buckets"]
        assert merged["total_seconds"] == pytest.approx(want["total_seconds"])
        if want["count"]:
            assert merged["min_seconds"] == want["min_seconds"]
            assert merged["max_seconds"] == want["max_seconds"]
            assert merged["p50_seconds"] == want["p50_seconds"]
            assert merged["p99_seconds"] == want["p99_seconds"]


def test_histogram_merge_rejects_foreign_edges():
    """A snapshot whose bucket edges are not the fixed ones cannot be
    merged exactly — refusing is the honest move."""
    h = LatencyHistogram()
    h.observe(0.01)
    snap = h.snapshot()
    snap["buckets"] = [[0.123456, 1]]  # not a registry edge
    with pytest.raises(ValueError):
        LatencyHistogram().merge_snapshot(snap)


# ---------------------------------------------------------------------------
# Prometheus exposition: renderer + stdlib parser round-trip
# ---------------------------------------------------------------------------


def _solo_doc() -> dict:
    h = LatencyHistogram()
    for v in (1e-4, 2e-4, 5e-3, 1.5):
        h.observe(v)
    return {
        "process": {
            "role": "solo", "worker_id": 'w"esc\\ape\n', "pid": 1,
            "started_at": 0.0, "uptime_s": 12.5,
        },
        "counters": {"pods_scheduled": 7, "scheduling_passes": 3},
        "timings": {"engine": h.snapshot()},
        "trace": {
            "enabled": True,
            "events": {"fault.fired": 2},
            "histograms": {},
            "ring": {"appended": 10, "size": 8, "evicted": 2},
        },
        "faults": {"replay.dispatch": {"calls": 5, "fired": 1}},
        "jobs": {
            "queue": {"depth": 1, "capacity": 16},
            "workers": {"pool": 2, "active": 1},
        },
    }


def test_prometheus_render_golden_and_roundtrip():
    """The exposition format is pinned by parse, not by hope: HELP/TYPE
    lines precede samples, label values escape backslash/quote/newline,
    histograms render cumulative ``le`` buckets incl. ``+Inf`` equal to
    ``_count``, and every family is in the lint-enforced registry."""
    text = render_prometheus(_solo_doc())
    lines = text.splitlines()
    assert "# TYPE ksim_counter_total counter" in lines
    assert "# TYPE ksim_latency_seconds histogram" in lines
    # Label escaping: the worker id carries \ " and a newline.
    assert '\\"esc\\\\ape\\n' in text
    # Counters carry the name label; faults the site label.
    assert any(
        l.startswith("ksim_counter_total{") and 'name="pods_scheduled"' in l
        and l.endswith(" 7") for l in lines
    )
    assert any(
        'site="replay.dispatch"' in l and l.startswith("ksim_fault_fired_total")
        for l in lines
    )
    fams = parse_prometheus(text)
    assert set(fams) <= set(obs.METRIC_NAMES)
    hist = fams["ksim_latency_seconds"]
    buckets = [
        s for s in hist["samples"] if s["name"] == "ksim_latency_seconds_bucket"
    ]
    # Full edge set + +Inf, cumulative, +Inf == _count.
    assert len(buckets) == len(LatencyHistogram.EDGES) + 1
    values = [s["value"] for s in buckets]
    assert values == sorted(values)
    inf = [s for s in buckets if s["labels"]["le"] == "+Inf"]
    count = [
        s for s in hist["samples"] if s["name"] == "ksim_latency_seconds_count"
    ]
    assert inf[0]["value"] == count[0]["value"] == 4
    gauges = parse_prometheus(text)["ksim_queue_depth"]
    assert gauges["samples"][0]["value"] == 1


def test_prometheus_parser_rejects_malformed():
    bad = [
        # sample without TYPE
        "ksim_up 1\n",
        # bucket without le
        "# TYPE ksim_latency_seconds histogram\n"
        "ksim_latency_seconds_bucket 3\n",
        # missing +Inf bucket
        "# TYPE ksim_latency_seconds histogram\n"
        'ksim_latency_seconds_bucket{le="0.001"} 3\n'
        "ksim_latency_seconds_sum 1\nksim_latency_seconds_count 3\n",
        # non-cumulative buckets
        "# TYPE ksim_latency_seconds histogram\n"
        'ksim_latency_seconds_bucket{le="0.001"} 3\n'
        'ksim_latency_seconds_bucket{le="+Inf"} 2\n'
        "ksim_latency_seconds_sum 1\nksim_latency_seconds_count 2\n",
        # +Inf != _count
        "# TYPE ksim_latency_seconds histogram\n"
        'ksim_latency_seconds_bucket{le="+Inf"} 2\n'
        "ksim_latency_seconds_sum 1\nksim_latency_seconds_count 3\n",
        # unterminated label value
        "# TYPE ksim_up gauge\n" 'ksim_up{worker="w 1\n',
    ]
    for text in bad:
        with pytest.raises(ValueError):
            parse_prometheus(text)


# ---------------------------------------------------------------------------
# Publishing + fleet-document merging
# ---------------------------------------------------------------------------


def _worker_doc(wid: str, *, published_at: float, claims: int) -> dict:
    h = LatencyHistogram()
    h.observe(0.002 * (claims + 1))
    return {
        "process": {
            "role": "worker", "worker_id": wid, "pid": 100, "started_at": 0.0,
            "uptime_s": 1.0, "seq": 1, "published_at": published_at,
            "publish_s": 1.0,
        },
        "counters": {"fleet_claims": claims},
        "timings": {},
        "trace": {
            "enabled": True, "events": {"jobs.fleet_claim": claims},
            "histograms": {"replay.dispatch": h.snapshot()},
        },
        "faults": {"replay.dispatch": {"calls": claims, "fired": 0}},
    }


def test_publish_snapshot_is_crash_atomic(tmp_path):
    doc = _worker_doc("wa", published_at=time.time(), claims=2)
    path = publish_snapshot(str(tmp_path), doc, worker_id="wa")
    assert os.path.basename(path) == "wa.json"
    on_disk = json.load(open(path))
    assert on_disk == json.loads(json.dumps(doc))
    # tmp files never survive a successful publish.
    assert [f for f in os.listdir(os.path.dirname(path)) if ".tmp" in f] == []
    docs = obs.read_fleet_snapshots(str(tmp_path))
    assert set(docs) == {"wa"}
    # A torn/corrupt sibling is skipped, never fatal.
    with open(os.path.join(str(tmp_path), obs.OBS_DIR, "wb.json"), "w") as f:
        f.write('{"truncated": ')
    assert set(obs.read_fleet_snapshots(str(tmp_path))) == {"wa"}


def test_fleet_merge_sums_and_flags_stale_worker():
    """Counters sum, histograms merge bucket-wise, and the dead worker
    surfaces as ``stale_s > 0`` with its identity intact — NEVER
    silently dropped."""
    now = time.time()
    docs = {
        "wa": _worker_doc("wa", published_at=now, claims=2),
        "wb": _worker_doc("wb", published_at=now - 300, claims=3),
    }
    merged = merge_fleet_docs(docs, now=now)
    assert merged["scope"] == "fleet"
    assert merged["counters"]["fleet_claims"] == 5
    assert merged["trace"]["events"]["jobs.fleet_claim"] == 5
    assert merged["faults"]["replay.dispatch"]["calls"] == 5
    assert merged["timings"]["replay.dispatch"]["count"] == 2
    assert set(merged["workers"]) == {"wa", "wb"}
    wa, wb = merged["workers"]["wa"], merged["workers"]["wb"]
    assert wa["stale"] is False and 0 <= wa["stale_s"] < 1
    assert wb["stale"] is True and wb["stale_s"] > 0
    assert wb["process"]["worker_id"] == "wb"  # identity survives death
    # Fleet exposition: per-worker series, ksim_up 0 for the stale one.
    fams = parse_prometheus(render_prometheus(merged))
    ups = {
        s["labels"]["worker"]: s["value"] for s in fams["ksim_up"]["samples"]
    }
    assert ups == {"wa": 1, "wb": 0}
    ages = {
        s["labels"]["worker"]: s["value"]
        for s in fams["ksim_snapshot_age_seconds"]["samples"]
    }
    assert ages["wb"] > ages["wa"]
    # A scraper's sum() over per-worker series re-derives the totals.
    claims = sum(
        s["value"]
        for s in fams["ksim_counter_total"]["samples"]
        if s["labels"]["name"] == "fleet_claims"
    )
    assert claims == merged["counters"]["fleet_claims"]


def test_merge_chrome_traces_lanes_epochs_and_flows():
    """One process lane per worker, timestamps rebased onto the oldest
    worker's epoch, and the submit→claim→run path stitched as one
    complete s/t/f flow triple (incomplete paths emit nothing)."""
    def tr(pid, epoch, events):
        return {
            "traceEvents": [
                {
                    "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                    "args": {"name": f"seed{pid}"},
                },
                *events,
            ],
            "displayTimeUnit": "ms",
            "otherData": {"epoch_unix_s": epoch},
        }

    claim = {
        "name": "jobs.fleet_claim", "ph": "X", "pid": 2, "tid": 1,
        "ts": 10.0, "dur": 5.0, "args": {"job": "j1"},
    }
    run = {
        "name": "jobs.run", "ph": "X", "pid": 2, "tid": 1,
        "ts": 30.0, "dur": 50.0, "args": {"job": "j1"},
    }
    enq = {
        "name": "jobs.enqueue", "ph": "X", "pid": 1, "tid": 1,
        "ts": 5.0, "dur": 1.0, "args": {"job": "j1"},
    }
    orphan = {  # j2 never claimed: no flow events for it
        "name": "jobs.enqueue", "ph": "X", "pid": 1, "tid": 1,
        "ts": 7.0, "dur": 1.0, "args": {"job": "j2"},
    }
    docs = {
        "fd": tr(1, 100.0, [enq, orphan]),
        "w1": tr(2, 102.0, [claim, run]),
    }
    merged = merge_chrome_traces(docs, flows=True)
    evs = merged["traceEvents"]
    # pid 1 and 2 each keep their own (pre-named) lane; no duplicates.
    names = [e for e in evs if e.get("ph") == "M" and e["name"] == "process_name"]
    assert sorted(e["args"]["name"] for e in names) == ["seed1", "seed2"]
    # w1's epoch is 2 s after fd's: its events shift by +2e6 us.
    by_name: dict = {}
    for e in evs:
        if e.get("ph") == "X":
            by_name.setdefault(e["name"], []).append(e["ts"])
    assert by_name["jobs.fleet_claim"] == [pytest.approx(10.0 + 2e6)]
    assert sorted(by_name["jobs.enqueue"]) == [5.0, 7.0]
    assert merged["otherData"]["merged"] == ["fd", "w1"]
    flows = [e for e in evs if e.get("name") == "jobs.flow"]
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    assert {f["args"]["job"] for f in flows} == {"j1"}
    assert len({f["id"] for f in flows}) == 1
    s, t, f = flows
    assert (s["pid"], t["pid"], f["pid"]) == (1, 2, 2)
    assert s["ts"] <= t["ts"] <= f["ts"]


# ---------------------------------------------------------------------------
# The publisher thread (in-process worker)
# ---------------------------------------------------------------------------


def _tiny_doc() -> dict:
    ops = [
        {"step": 0, "createOperation": {"object": make_node("n0", cpu="4")}},
        {"step": 1, "createOperation": {"object": make_pod("p0", cpu="100m")}},
    ]
    return {"spec": {"scenario": {"operations": ops}}}


def test_worker_publishes_on_cadence_and_at_shutdown(tmp_path, monkeypatch):
    from ksim_tpu.jobs import JobManager

    monkeypatch.setenv("KSIM_OBS_PUBLISH_S", "0.2")
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        role="worker", worker_id="wpub", lease_s=5.0, poll_s=0.1,
    )
    try:
        assert jm._fleet._publish_thread is not None
        deadline = time.monotonic() + 30
        path = os.path.join(str(tmp_path), obs.OBS_DIR, "wpub.json")
        while not os.path.exists(path):
            assert time.monotonic() < deadline, "no snapshot published"
            time.sleep(0.05)
        doc = obs.read_fleet_snapshots(str(tmp_path))["wpub"]
        ident = doc["process"]
        assert ident["role"] == "worker" and ident["worker_id"] == "wpub"
        assert ident["pid"] == os.getpid() and ident["seq"] >= 1
        assert ident["publish_s"] == pytest.approx(0.2)
        assert set(doc) >= {
            "process", "counters", "timings", "trace", "faults", "jobs",
        }
        first_seq = ident["seq"]
    finally:
        jm.shutdown()
    # Shutdown publishes one final snapshot AFTER the drain.
    final = obs.read_fleet_snapshots(str(tmp_path))["wpub"]
    assert final["process"]["seq"] > first_seq


def test_zero_cadence_means_no_thread_and_no_directory(tmp_path, monkeypatch):
    """The zero-perturbation contract: KSIM_OBS_PUBLISH_S=0 creates no
    publisher thread and never materializes KSIM_JOBS_DIR/obs/."""
    from ksim_tpu.jobs import JobManager

    monkeypatch.setenv("KSIM_OBS_PUBLISH_S", "0")
    jm = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        role="worker", worker_id="woff", lease_s=5.0, poll_s=0.1,
    )
    try:
        assert jm._fleet._publish_thread is None
        assert not any(
            t.name.startswith("obs-publish")
            for t in __import__("threading").enumerate()
        )
    finally:
        jm.shutdown()
    assert not os.path.exists(os.path.join(str(tmp_path), obs.OBS_DIR))


# ---------------------------------------------------------------------------
# 2-process fleet smoke (the `make obs-check` leg)
# ---------------------------------------------------------------------------


def _spawn_worker(tmp_path, worker_id: str) -> subprocess.Popen:
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ksim_tpu.jobs",
            "--dir", str(tmp_path), "--worker-id", worker_id,
            "--workers", "1",
        ],
        env=sanitized_cpu_env({
            "KSIM_WORKERS_LEASE_S": "30",
            "KSIM_WORKERS_POLL_S": "0.2",
            "KSIM_OBS_PUBLISH_S": "0.5",
        }),
        cwd="/root/repo",
        stdout=subprocess.PIPE,
        text=True,
    )
    line = proc.stdout.readline()
    assert line.strip() == f"READY {worker_id}", line
    return proc


def _http(port, path):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", path)
    r = c.getresponse()
    data = r.read().decode()
    c.close()
    return r.status, data


@pytest.mark.slow
def test_two_worker_fleet_scrape_counter_sums_and_staleness(
    tmp_path, monkeypatch
):
    """The acceptance scenario: 2 worker processes + in-process front
    door.  The fleet-scope document's counter sums equal the per-worker
    sums, both workers are identity-attributed, the exposition parses
    clean, and a SIGKILLed worker turns ``stale_s > 0`` while staying
    in the document."""
    from ksim_tpu.server import DIContainer, SimulatorServer

    monkeypatch.setenv("KSIM_JOBS_DIR", str(tmp_path))
    monkeypatch.setenv("KSIM_WORKERS_ROLE", "frontdoor")
    monkeypatch.setenv("KSIM_WORKER_ID", "fd")
    monkeypatch.setenv("KSIM_WORKERS_POLL_S", "0.1")
    monkeypatch.setenv("KSIM_OBS_PUBLISH_S", "0.5")
    procs = {
        "wA": _spawn_worker(tmp_path, "wA"),
        "wB": _spawn_worker(tmp_path, "wB"),
    }
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    try:
        jm = di.job_manager
        jobs = [jm.submit(_tiny_doc()) for _ in range(4)]
        deadline = time.monotonic() + 120
        for job in jobs:
            while job.status()["state"] not in ("succeeded", "failed"):
                assert time.monotonic() < deadline, job.status()
                time.sleep(0.1)
            assert job.status()["state"] == "succeeded", job.status()

        def fleet_doc():
            status, body = _http(srv.port, "/api/v1/metrics?scope=fleet")
            assert status == 200
            return json.loads(body)

        # Wait until every worker's published snapshot has caught up
        # with the 4 claims (publish cadence 0.5 s).
        while True:
            doc = fleet_doc()
            done = {"fd", "wA", "wB"} <= set(doc["workers"]) and (
                doc["counters"].get("fleet_claims") == 4
            )
            if done:
                break
            assert time.monotonic() < deadline, doc.get("workers", {}).keys()
            time.sleep(0.2)
        per_worker = [
            w.get("counters", {}).get("fleet_claims", 0)
            for w in doc["workers"].values()
        ]
        assert sum(per_worker) == doc["counters"]["fleet_claims"] == 4
        for wid in ("wA", "wB"):
            ident = doc["workers"][wid]["process"]
            assert ident["worker_id"] == wid and ident["role"] == "worker"
            assert doc["workers"][wid]["stale"] is False
        # The exposition endpoint renders the same document, parseable.
        status, text = _http(srv.port, "/metrics?scope=fleet")
        assert status == 200
        fams = parse_prometheus(text)
        assert set(fams) <= set(obs.METRIC_NAMES)
        claims = sum(
            s["value"]
            for s in fams["ksim_counter_total"]["samples"]
            if s["labels"]["name"] == "fleet_claims"
        )
        assert claims == 4

        # Kill wB: past the staleness bound it flags, never drops.
        procs["wB"].kill()
        procs["wB"].wait()
        while True:
            doc = fleet_doc()
            wb = doc["workers"].get("wB")
            assert wb is not None, "dead worker dropped from the document"
            if wb["stale"]:
                break
            assert time.monotonic() < deadline + 60, wb
            time.sleep(0.2)
        assert wb["stale_s"] > 0
        assert wb["process"]["worker_id"] == "wB"
        assert doc["workers"]["wA"]["stale"] is False
        # Stale-but-present in the exposition too: ksim_up 0.
        _, text = _http(srv.port, "/metrics?scope=fleet")
        ups = {
            s["labels"]["worker"]: s["value"]
            for s in parse_prometheus(text)["ksim_up"]["samples"]
        }
        assert ups["wB"] == 0 and ups["wA"] == 1
    finally:
        for proc in procs.values():
            proc.kill()
            proc.wait()
        srv.shutdown_server()
        di.shutdown()
