"""Permit extension point + extender managedResources gating.

Reference semantics under test: per-plugin permit status/timeout
annotations (wrappedplugin.go:582-611, store.go:549-560), waiting-pod
allow/reject/timeout (upstream framework waitingPodsMap), and extenders
engaging only for pods that request a managed resource
(extender.go:99-112).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ksim_tpu.engine.annotations import (
    BIND_RESULT_KEY,
    PERMIT_RESULT_KEY,
    PERMIT_TIMEOUT_RESULT_KEY,
    RESERVE_RESULT_KEY,
    SELECTED_NODE_KEY,
)
from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.scheduler.permit import PermitResult, go_duration_str
from ksim_tpu.state.cluster import ClusterStore
from tests.helpers import make_node, make_pod


def test_go_duration_str():
    # Byte-parity with Go time.Duration.String().
    assert go_duration_str(0) == "0s"
    assert go_duration_str(10) == "10s"
    assert go_duration_str(90) == "1m30s"
    assert go_duration_str(3600) == "1h0m0s"
    assert go_duration_str(1.5) == "1.5s"
    assert go_duration_str(0.5) == "500ms"
    assert go_duration_str(0.0005) == "500µs"
    assert go_duration_str(30) == "30s"


class _PermitPlugin:
    """Out-of-tree plugin implementing only the Permit point."""

    name = "GatePlugin"

    def __init__(self, result: PermitResult) -> None:
        self.result = result
        self.calls: list[tuple[str, str]] = []

    def permit(self, pod, node_name):
        self.calls.append((pod["metadata"]["name"], node_name))
        return self.result


def _service_with_permit(store, plugin):
    def build(feats, args):
        return ScoredPlugin(plugin, filter_enabled=False, score_enabled=False)

    return SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"permit": {"enabled": [{"name": plugin.name}]}}}
            ]
        },
        registry={plugin.name: build},
    )


def _store(*pods):
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    for p in pods:
        store.create("pods", p)
    return store


def test_permit_allow_binds_and_records():
    plugin = _PermitPlugin(PermitResult.allow())
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    placements = svc.schedule_pending()
    assert placements["default/p1"] == "n1"
    assert plugin.calls == [("p1", "n1")]
    pod = store.get("pods", "p1", "default")
    assert pod["spec"]["nodeName"] == "n1"
    annos = pod["metadata"]["annotations"]
    assert json.loads(annos[PERMIT_RESULT_KEY]) == {"GatePlugin": "success"}
    assert json.loads(annos[PERMIT_TIMEOUT_RESULT_KEY]) == {"GatePlugin": "0s"}


def test_permit_reject_blocks_bind_keeps_reserve_records():
    plugin = _PermitPlugin(PermitResult.reject("quota exhausted"))
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    placements = svc.schedule_pending()
    assert placements["default/p1"] is None
    pod = store.get("pods", "p1", "default")
    assert "nodeName" not in pod["spec"]
    annos = pod["metadata"]["annotations"]
    assert json.loads(annos[PERMIT_RESULT_KEY]) == {"GatePlugin": "quota exhausted"}
    # Reserve ran (selected-node recorded, upstream AddSelectedNode at
    # Reserve) but Bind never did.
    assert annos[SELECTED_NODE_KEY] == "n1"
    assert json.loads(annos[BIND_RESULT_KEY]) == {}
    assert RESERVE_RESULT_KEY in annos


def test_permit_wait_parks_then_allow_binds():
    plugin = _PermitPlugin(PermitResult.wait(30))
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    placements = svc.schedule_pending()
    assert placements["default/p1"] == "n1"
    # Parked: not bound, not pending, visible via the waiting API.
    assert "nodeName" not in store.get("pods", "p1", "default")["spec"]
    waiting = svc.get_waiting_pods()
    assert waiting == [
        {
            "name": "p1",
            "namespace": "default",
            "nodeName": "n1",
            "pendingPlugins": ["GatePlugin"],
        }
    ]
    assert svc.pending_count() == 0
    # A second pass must not re-schedule the waiter.
    assert svc.schedule_pending() == {}
    # Allow -> binds with the recorded wait status/timeout.
    assert svc.allow_waiting_pod("p1")
    pod = store.get("pods", "p1", "default")
    assert pod["spec"]["nodeName"] == "n1"
    annos = pod["metadata"]["annotations"]
    assert json.loads(annos[PERMIT_RESULT_KEY]) == {"GatePlugin": "wait"}
    assert json.loads(annos[PERMIT_TIMEOUT_RESULT_KEY]) == {"GatePlugin": "30s"}
    assert json.loads(annos[BIND_RESULT_KEY]) == {"DefaultBinder": "success"}
    assert svc.get_waiting_pods() == []


def test_permit_waiting_pod_charges_node_capacity():
    # n1 fits ONE of these pods; while the first waits on permit, the
    # second must not land on n1 (assumed-pod accounting).
    plugin = _PermitPlugin(PermitResult.wait(30))
    store = ClusterStore()
    store.create("nodes", make_node("n1", cpu="1", memory="1Gi"))
    store.create("pods", make_pod("p1", cpu="800m"))
    svc = _service_with_permit(store, plugin)
    svc.schedule_pending()
    assert svc.get_waiting_pods()[0]["name"] == "p1"
    store.create("pods", make_pod("p2", cpu="800m"))
    placements = svc.schedule_pending()
    assert placements["default/p2"] is None  # n1 is full with the waiter


def test_permit_wait_timeout_rejects():
    plugin = _PermitPlugin(PermitResult.wait(0.2))
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    svc.schedule_pending()
    assert len(svc.get_waiting_pods()) == 1
    time.sleep(0.25)
    assert svc._expire_waiting() == 1
    pod = store.get("pods", "p1", "default")
    assert "nodeName" not in pod["spec"]
    annos = pod["metadata"]["annotations"]
    assert json.loads(annos[PERMIT_RESULT_KEY]) == {"GatePlugin": "wait"}
    assert json.loads(annos[BIND_RESULT_KEY]) == {}
    # Back in the queue (after backoff) — not parked anymore.
    assert svc.get_waiting_pods() == []


def test_reject_waiting_pod_api():
    plugin = _PermitPlugin(PermitResult.wait(30))
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    svc.schedule_pending()
    assert svc.reject_waiting_pod("p1", message="operator said no")
    assert svc.get_waiting_pods() == []
    assert "nodeName" not in store.get("pods", "p1", "default")["spec"]
    # Unknown pod -> False.
    assert not svc.reject_waiting_pod("nope")


def test_rejected_waiter_is_retried_and_binds():
    """A rejected waiter must not stall in an idle cluster: the watch
    loop's poked/periodic passes retry it past its backoff (upstream's
    wall-clock backoff queue drains on timers, not only cluster events)."""

    class FlipGate:
        name = "FlipGate"

        def __init__(self):
            self.calls = 0

        def permit(self, pod, node_name):
            self.calls += 1
            return PermitResult.wait(300) if self.calls == 1 else PermitResult.allow()

    plugin = FlipGate()
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    svc.start()
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not svc.get_waiting_pods():
            time.sleep(0.05)
        assert svc.get_waiting_pods(), "pod never parked"
        assert svc.reject_waiting_pod("p1", message="operator")
        # No further cluster events: the retry must come from the loop.
        deadline = time.time() + 120
        bound = None
        while time.time() < deadline and not bound:
            bound = store.get("pods", "p1", "default")["spec"].get("nodeName")
            time.sleep(0.1)
        assert bound == "n1", "rejected waiter was never retried"
    finally:
        svc.stop()


# -- extender managedResources gating ---------------------------------------


class _CountingExtender(BaseHTTPRequestHandler):
    calls: list[str] = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        type(self).calls.append(body["pod"]["metadata"]["name"])
        names = body.get("nodenames") or []
        if self.path.endswith("/filter"):
            out = {"nodenames": names}
        else:
            out = [{"host": n, "score": 1} for n in names]
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture()
def counting_extender():
    _CountingExtender.calls = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _CountingExtender)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def test_extender_managed_resources_gate(counting_extender):
    store = ClusterStore()
    store.create("nodes", make_node("n1", extra_alloc={"example.com/gpu": "4"}))
    store.create("pods", make_pod("plain"))
    gpu_pod = make_pod("gpu-pod", extra_requests={"example.com/gpu": "1"})
    store.create("pods", gpu_pod)
    svc = SchedulerService(
        store,
        config={
            "extenders": [
                {
                    "urlPrefix": counting_extender,
                    "filterVerb": "filter",
                    "prioritizeVerb": "prioritize",
                    "nodeCacheCapable": True,
                    "managedResources": [{"name": "example.com/gpu"}],
                }
            ]
        },
    )
    placements = svc.schedule_pending()
    assert placements["default/plain"] == "n1"
    assert placements["default/gpu-pod"] == "n1"
    # Only the gpu pod engaged the extender (filter + prioritize).
    assert set(_CountingExtender.calls) == {"gpu-pod"}


def test_permit_runs_on_extender_path(counting_extender):
    """Permit must gate binding on the per-pod extender path too."""
    plugin = _PermitPlugin(PermitResult.wait(30))

    def build(feats, args):
        return ScoredPlugin(plugin, filter_enabled=False, score_enabled=False)

    store = _store(make_pod("p1"))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"permit": {"enabled": [{"name": plugin.name}]}}}
            ],
            "extenders": [
                {
                    "urlPrefix": counting_extender,
                    "filterVerb": "filter",
                    "nodeCacheCapable": True,
                }
            ],
        },
        registry={plugin.name: build},
    )
    svc.schedule_pending()
    assert plugin.calls == [("p1", "n1")]
    assert "nodeName" not in store.get("pods", "p1", "default")["spec"]
    assert svc.get_waiting_pods()[0]["name"] == "p1"
    assert svc.allow_waiting_pod("p1")
    assert store.get("pods", "p1", "default")["spec"]["nodeName"] == "n1"


def test_deleting_waiting_pod_clears_entry():
    """A deleted waiter's entry dies with it: a re-created same-name pod
    schedules fresh instead of inheriting the stale wait."""
    plugin = _PermitPlugin(PermitResult.wait(900))
    store = _store(make_pod("p1"))
    svc = _service_with_permit(store, plugin)
    svc.start()
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not svc.get_waiting_pods():
            time.sleep(0.05)
        assert svc.get_waiting_pods()
        store.delete("pods", "p1", "default")
        deadline = time.time() + 30
        while time.time() < deadline and svc.get_waiting_pods():
            time.sleep(0.05)
        assert svc.get_waiting_pods() == []
        # Re-created pod is pending again (parks anew on the next pass).
        store.create("pods", make_pod("p1"))
        deadline = time.time() + 120
        while time.time() < deadline and not svc.get_waiting_pods():
            time.sleep(0.05)
        assert svc.get_waiting_pods()[0]["name"] == "p1"
    finally:
        svc.stop()


def test_permit_first_reject_stops_later_plugins():
    """Upstream RunPermitPlugins returns on the first failure; later
    plugins neither run nor record."""
    rejecter = _PermitPlugin(PermitResult.reject("no"))
    rejecter.name = "A-Reject"
    after = _PermitPlugin(PermitResult.allow())
    after.name = "B-After"

    def build_r(feats, args):
        return ScoredPlugin(rejecter, filter_enabled=False, score_enabled=False)

    def build_a(feats, args):
        return ScoredPlugin(after, filter_enabled=False, score_enabled=False)

    store = _store(make_pod("p1"))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {
                    "plugins": {
                        "permit": {
                            "enabled": [{"name": "A-Reject"}, {"name": "B-After"}]
                        }
                    }
                }
            ]
        },
        registry={"A-Reject": build_r, "B-After": build_a},
    )
    svc.schedule_pending()
    assert after.calls == []
    annos = store.get("pods", "p1", "default")["metadata"]["annotations"]
    assert json.loads(annos[PERMIT_RESULT_KEY]) == {"A-Reject": "no"}


def test_extender_without_managed_resources_sees_all(counting_extender):
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("plain"))
    svc = SchedulerService(
        store,
        config={
            "extenders": [
                {
                    "urlPrefix": counting_extender,
                    "filterVerb": "filter",
                    "nodeCacheCapable": True,
                }
            ]
        },
    )
    assert svc.schedule_pending()["default/plain"] == "n1"
    assert _CountingExtender.calls == ["plain"]


def test_waiting_pods_http_surface():
    """GET /api/v1/waitingpods + POST .../allow|reject — the REST form of
    the framework handle for external permit controllers."""
    import http.client

    from ksim_tpu.server import DIContainer, SimulatorServer

    plugin = _PermitPlugin(PermitResult.wait(300))

    def build(feats, args):
        return ScoredPlugin(plugin, filter_enabled=False, score_enabled=False)

    di = DIContainer(
        scheduler_config={
            "profiles": [
                {"plugins": {"permit": {"enabled": [{"name": plugin.name}]}}}
            ]
        },
        registry={plugin.name: build},
    )
    di.store.create("nodes", make_node("n1"))
    di.store.create("pods", make_pod("p1"))
    di.store.create("pods", make_pod("p2"))
    srv = SimulatorServer(di, port=0).start()

    def req(method, path, body=None):
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        c.request(method, path, json.dumps(body) if body is not None else None,
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        data = r.read()
        c.close()
        return r.status, json.loads(data) if data else None

    try:
        di.scheduler_service.schedule_pending()
        status, out = req("GET", "/api/v1/waitingpods")
        assert status == 200
        assert sorted(w["name"] for w in out["items"]) == ["p1", "p2"]
        # Allow one over REST -> binds.
        status, _ = req("POST", "/api/v1/waitingpods/default/p1/allow")
        assert status == 200
        assert di.store.get("pods", "p1", "default")["spec"]["nodeName"]
        # Reject the other -> back to pending, annotations recorded.
        status, _ = req(
            "POST", "/api/v1/waitingpods/default/p2/reject",
            {"message": "external controller said no"},
        )
        assert status == 200
        assert "nodeName" not in di.store.get("pods", "p2", "default")["spec"]
        # Gone now.
        status, _ = req("POST", "/api/v1/waitingpods/default/p2/allow")
        assert status == 404
        status, out = req("GET", "/api/v1/waitingpods")
        assert out["items"] == []
    finally:
        srv.shutdown_server()
        di.shutdown()
