"""UI page checks: a static id/handler/function contract (always runs)
and a real DOM smoke executing the page's JS (runs when a JS runtime is
on PATH; this image ships none — no node/bun/chromium — so it skips
here, like the TPU tier does without a chip, and runs in any dev
environment with node).  The reference's jest config
(web/jest.config.js) is the same idea for its Nuxt app.
"""

from __future__ import annotations

import re
import shutil
import subprocess

import pytest

from ksim_tpu.server.ui import INDEX_HTML


def _script() -> str:
    m = re.search(r"<script>(.*)</script>", INDEX_HTML, re.S)
    assert m, "no script block in INDEX_HTML"
    return m.group(1)


def _html_no_script() -> str:
    return re.sub(r"<script>.*</script>", "", INDEX_HTML, flags=re.S)


def test_ui_static_contract():
    """Every onclick handler resolves to a defined function; every
    getElementById target exists; the script block is brace-balanced
    (catches truncation/renames without a JS runtime)."""
    script = _script()
    html = _html_no_script()
    defined = set(
        re.findall(r"(?:async\s+)?function\s+([A-Za-z_]\w*)\s*\(", script)
    ) | set(re.findall(r"(?:let|const)\s+([A-Za-z_]\w*)\s*=", script))
    for fn in re.findall(r'onclick="([A-Za-z_]\w*)\s*\(', html):
        assert fn in defined, f"onclick handler {fn}() is not defined in the script"
    ids = set(re.findall(r'id="([^"]+)"', html))
    for target in re.findall(r'getElementById\("([^"]+)"\)', script):
        assert target in ids, f"getElementById({target!r}) has no matching id="
    # The render pipeline's load-bearing functions exist by name.
    for fn in ("render", "renderBoard", "renderBoardNow", "showResults", "watch"):
        assert fn in defined, f"function {fn} missing from the UI script"
    for ch_open, ch_close in ("{}", "()", "[]"):
        assert script.count(ch_open) == script.count(ch_close), (
            f"unbalanced {ch_open}{ch_close} in UI script"
        )
    # Result categories track the annotation contract.
    from ksim_tpu.engine.annotations import ALL_RESULT_KEYS, PREFIX

    cats = re.search(r"RESULT_CATS = \[(.*?)\]", script, re.S)
    assert cats
    for cat in re.findall(r'"([a-z-]+)"', cats.group(1)):
        assert PREFIX + cat in ALL_RESULT_KEYS


_DOM_SHIM = r"""
// Minimal DOM/fetch shim: enough surface for the simulator page's
// render pipeline (innerHTML as strings; querySelector* answered by
// regex over the stored HTML).
class El {
  constructor(id) { this.id = id; this._html = ""; this.style = {display: ""};
    this.dataset = {}; this.onclick = null; this.value = ""; this.textContent = ""; }
  set innerHTML(h) { this._html = h; }
  get innerHTML() { return this._html; }
  insertAdjacentHTML(_pos, h) { this._html += h; }
  querySelectorAll(sel) {
    // Count matches by class or attribute pattern; return stubs with
    // dataset populated from data-* attributes in the matched tag.
    const out = [];
    const cls = sel.startsWith(".") ? sel.slice(1) : null;
    const attr = sel.match(/^(\w+)?\[data-(\w+)\]$/);
    const re = cls
      ? new RegExp(`<[^>]*class="[^"]*${cls}[^"]*"[^>]*>`, "g")
      : attr ? new RegExp(`<${attr[1] || "\\w+"}[^>]*data-${attr[2]}="[^"]*"[^>]*>`, "g")
      : null;
    if (!re) return out;
    for (const m of this._html.matchAll(re)) {
      const el = new El();
      for (const am of m[0].matchAll(/data-(\w+)="([^"]*)"/g)) el.dataset[am[1]] = am[2];
      out.push(el);
    }
    return out;
  }
  querySelector(sel) { return byId["__q__" + sel] || (byId["__q__" + sel] = new El()); }
}
const byId = {};
const document = {
  getElementById: (id) => byId[id] || (byId[id] = new El(id)),
  querySelector: (sel) => byId["__q__" + sel] || (byId["__q__" + sel] = new El()),
  querySelectorAll: (sel) => (byId["__body__"] || new El()).querySelectorAll(sel),
  createElement: () => new El(),
};
const fetch = () => new Promise(() => {});  // watch() parks forever
const URL = { createObjectURL: () => "" };
globalThis.document = document; globalThis.fetch = fetch; globalThis.URL = URL;
"""

_DOM_ASSERTS = r"""
// Feed two watch-shaped events straight into the store, then exercise
// the render pipeline the way the stream handler does.
store.pods.set("default/web-1", {metadata: {name: "web-1", namespace: "default",
  annotations: {[PREFIX + "selected-node"]: "node-a",
    [PREFIX + "filter-result"]: JSON.stringify({"node-a": {NodeName: "passed"}}),
    [PREFIX + "result-history"]: "[]"}},
  spec: {nodeName: "node-a"}, status: {phase: "Running"}});
store.nodes.set("node-a", {metadata: {name: "node-a"},
  status: {allocatable: {cpu: "4", memory: "8Gi", pods: "110"}}});
render();
const tabs = document.getElementById("tabs").innerHTML;
if (!tabs.includes("pods (1)")) throw new Error("tabs did not render: " + tabs);
document.getElementById("boardPanel").style.display = "block";
renderBoardNow();
const board = document.getElementById("board").innerHTML;
if (!board.includes("node-a (1)")) throw new Error("board missing node bucket: " + board);
if (!board.includes("web-1")) throw new Error("board missing pod: " + board);
if (!board.includes("unscheduled (0)")) throw new Error("board missing unscheduled bucket");
showResults("default/web-1");
const results = document.getElementById("results").innerHTML;
if (!results.includes("filter-result")) throw new Error("results missing filter table: " + results);
if (!results.includes("NodeName")) throw new Error("results missing plugin column");
console.log("UI_SMOKE_OK");
"""


@pytest.mark.skipif(
    shutil.which("node") is None and shutil.which("bun") is None,
    reason="no JS runtime on PATH (this image ships none)",
)
def test_ui_dom_smoke(tmp_path):
    """Execute the page's actual JS against a DOM shim: two resources
    land in the store, render()/renderBoardNow()/showResults() produce
    the board and result tables.  A broken renderBoard fails here."""
    runtime = shutil.which("node") or shutil.which("bun")
    harness = _DOM_SHIM + "\n" + _script() + "\n" + _DOM_ASSERTS
    f = tmp_path / "ui_smoke.js"
    f.write_text(harness)
    proc = subprocess.run(
        [runtime, str(f)], capture_output=True, text=True, timeout=60
    )
    assert proc.returncode == 0, proc.stderr
    assert "UI_SMOKE_OK" in proc.stdout
