"""Extender webhook proxy: filtering/prioritizing through a fake HTTP
extender, result-store annotations, config override, and the proxy routes
(reference simulator/scheduler/extender/)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ksim_tpu.scheduler.extender import (
    EXTENDER_FILTER_RESULT_KEY,
    EXTENDER_PRIORITIZE_RESULT_KEY,
    ExtenderService,
    override_extenders_cfg_to_simulator,
)
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from tests.helpers import make_node, make_pod


class _FakeExtender(BaseHTTPRequestHandler):
    """A webhook that filters out nodes named *-banned and prefers
    *-favored (score 10, else 1)."""

    calls: list[tuple[str, dict]] = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        type(self).calls.append((self.path, body))
        if self.path.endswith("/filter"):
            names = body.get("nodenames") or [
                n["metadata"]["name"] for n in body["nodes"]["items"]
            ]
            keep = [n for n in names if not n.endswith("-banned")]
            out = {"nodenames": keep, "failedNodes": {
                n: "banned by extender" for n in names if n.endswith("-banned")}}
        elif self.path.endswith("/prioritize"):
            names = body.get("nodenames") or [
                n["metadata"]["name"] for n in body["nodes"]["items"]
            ]
            out = [
                {"host": n, "score": 10 if n.endswith("-favored") else 1}
                for n in names
            ]
        else:
            out = {}
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture()
def fake_extender():
    _FakeExtender.calls = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeExtender)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def _config(url, **extra):
    return {
        "extenders": [
            {
                "urlPrefix": url,
                "filterVerb": "filter",
                "prioritizeVerb": "prioritize",
                "weight": 1,
                "nodeCacheCapable": True,
                **extra,
            }
        ]
    }


def test_scheduling_respects_extender_filter_and_scores(fake_extender):
    store = ClusterStore()
    # big-favored would win on plugin scores alone? Make all equal-sized;
    # the extender's prioritize breaks the tie toward -favored, and its
    # filter bans -banned outright.
    store.create("nodes", make_node("a-banned", cpu="64", memory="128Gi"))
    store.create("nodes", make_node("b-plain"))
    store.create("nodes", make_node("c-favored"))
    store.create("pods", make_pod("p0", cpu="100m"))
    svc = SchedulerService(store, config=_config(fake_extender))
    placements = svc.schedule_pending()
    assert placements == {"default/p0": "c-favored"}
    pod = store.get("pods", "p0")
    filt = json.loads(pod["metadata"]["annotations"][EXTENDER_FILTER_RESULT_KEY])
    assert fake_extender in filt
    assert filt[fake_extender]["failedNodes"] == {"a-banned": "banned by extender"}
    prio = json.loads(pod["metadata"]["annotations"][EXTENDER_PRIORITIZE_RESULT_KEY])
    # Scores re-scaled by weight * (100/10).
    scores = {hp["host"]: hp["score"] for hp in prio[fake_extender]}
    assert scores["c-favored"] == 100 and scores["b-plain"] == 10


def test_extender_routes_over_http(fake_extender):
    from ksim_tpu.server import DIContainer, SimulatorServer
    import http.client

    di = DIContainer(scheduler_config=_config(fake_extender))
    srv = SimulatorServer(di, port=0).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        args = {"pod": make_pod("px"), "nodenames": ["n-banned", "n-ok"]}
        c.request("POST", "/api/v1/extender/filter/0", json.dumps(args),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        out = json.loads(r.read())
        assert r.status == 200
        assert out["nodenames"] == ["n-ok"]
        c.close()
    finally:
        srv.shutdown_server()
        di.shutdown()


def test_override_extenders_cfg():
    cfg = _config("https://real.example.com", enableHTTPS=True)
    out = override_extenders_cfg_to_simulator(cfg, 1212)
    e = out["extenders"][0]
    assert e["urlPrefix"] == "http://localhost:1212/api/v1/extender/"
    assert e["filterVerb"] == "filter/0"
    assert e["prioritizeVerb"] == "prioritize/0"
    assert e["enableHTTPS"] is False


def test_ignorable_extender_failure(fake_extender):
    store = ClusterStore()
    store.create("nodes", make_node("n0"))
    store.create("pods", make_pod("p0"))
    # Unreachable extender: ignorable -> pod still schedules.
    cfg = {
        "extenders": [
            {"urlPrefix": "http://127.0.0.1:1", "filterVerb": "filter",
             "ignorable": True}
        ]
    }
    svc = SchedulerService(store, config=cfg)
    assert svc.schedule_pending() == {"default/p0": "n0"}
    # Not ignorable -> pod stays pending.
    store2 = ClusterStore()
    store2.create("nodes", make_node("n0"))
    store2.create("pods", make_pod("p0"))
    cfg2 = {
        "extenders": [
            {"urlPrefix": "http://127.0.0.1:1", "filterVerb": "filter"}
        ]
    }
    svc2 = SchedulerService(store2, config=cfg2)
    assert svc2.schedule_pending() == {"default/p0": None}


def test_extender_preemption_still_runs(fake_extender):
    # With an extender configured, an unschedulable high-priority pod
    # still preempts (the per-pod path runs PostFilter too).
    store = ClusterStore()
    store.create("nodes", make_node("n0", cpu="2", memory="8Gi"))
    low = make_pod("low", cpu="2", memory=None, node_name="n0", priority=1)
    store.create("pods", low)
    store.create("pods", make_pod("crit", cpu="1", memory=None, priority=100))
    svc = SchedulerService(store, config=_config(fake_extender))
    assert svc.schedule_pending() == {"default/crit": None}
    crit = store.get("pods", "crit")
    assert crit["status"]["nominatedNodeName"] == "n0"
    assert [p["metadata"]["name"] for p in store.list("pods")] == ["crit"]
    assert svc.schedule_pending() == {"default/crit": "n0"}


def test_proxy_results_flushed_by_watch_loop(fake_extender):
    # An EXTERNAL scheduler drives the proxy route; the service's watch
    # loop reflects the recorded extender annotations onto the pod.
    import time as _time

    store = ClusterStore()
    store.create("nodes", make_node("n0"))
    svc = SchedulerService(store, config=_config(fake_extender))
    pod = make_pod("ext-pod")
    pod["spec"]["schedulerName"] = "someone-else"  # not ours to schedule
    store.create("pods", pod)
    svc.start()
    try:
        args = {"pod": store.get("pods", "ext-pod"), "nodenames": ["n0"]}
        svc.extender_service.filter(0, args)
        # Trigger a pod event (what the external scheduler's bind would do).
        store.patch("pods", "ext-pod", "default",
                    lambda o: o["spec"].__setitem__("nodeName", "n0"))
        deadline = _time.monotonic() + 5
        found = False
        while _time.monotonic() < deadline and not found:
            annos = store.get("pods", "ext-pod")["metadata"].get("annotations") or {}
            found = EXTENDER_FILTER_RESULT_KEY in annos
            _time.sleep(0.05)
        assert found
    finally:
        svc.stop()
