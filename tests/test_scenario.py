"""Scenario replay harness (KEP-140 analogue): operation application,
node-drain requeue, result aggregation, and generator invariants."""

from __future__ import annotations

from ksim_tpu.scenario import Operation, ScenarioRunner, churn_scenario
from tests.helpers import make_node, make_pod


def test_runner_basic_flow():
    runner = ScenarioRunner()
    ops = [
        Operation(step=0, op="create", kind="nodes", obj=make_node("n0", cpu="2")),
        Operation(step=1, op="create", kind="pods", obj=make_pod("a", cpu="1", memory=None)),
        Operation(step=1, op="create", kind="pods", obj=make_pod("b", cpu="1", memory=None)),
        Operation(step=2, op="create", kind="pods", obj=make_pod("c", cpu="1", memory=None)),
        Operation(step=3, op="delete", kind="pods", name="a", namespace="default"),
    ]
    res = runner.run(ops)
    assert res.events_applied == 5
    assert res.pods_scheduled == 3  # a, b at step 1; c after a's deletion
    # Step 2: c could not fit (2 cpu taken) -> one unschedulable attempt.
    assert res.steps[2].unschedulable == 1
    # Step 3: a deleted frees capacity, c binds.
    assert res.steps[3].scheduled == 1
    assert res.steps[3].pending_after == 0
    assert runner.store.get("pods", "c")["spec"]["nodeName"] == "n0"


def test_node_delete_requeues_pods():
    runner = ScenarioRunner()
    res = runner.run(
        [
            Operation(step=0, op="create", kind="nodes", obj=make_node("n0")),
            Operation(step=0, op="create", kind="nodes", obj=make_node("n1")),
            Operation(step=1, op="create", kind="pods", obj=make_pod("p", cpu="1")),
        ]
    )
    assert res.pods_scheduled == 1
    bound_to = runner.store.get("pods", "p")["spec"]["nodeName"]
    other = {"n0": "n1", "n1": "n0"}[bound_to]
    res2 = runner.run(
        [Operation(step=0, op="delete", kind="nodes", name=bound_to)]
    )
    # The drained node's pod was requeued and rescheduled onto the other.
    assert res2.pods_scheduled == 1
    assert runner.store.get("pods", "p")["spec"]["nodeName"] == other


def test_churn_generator_shape():
    ops = list(churn_scenario(0, n_nodes=50, n_events=600, ops_per_step=40))
    assert sum(1 for o in ops if o.step == 0) == 50  # node bootstrap
    assert len(ops) >= 600
    kinds = {o.op for o in ops}
    assert kinds == {"create", "delete"}
    # Deterministic for equal seeds.
    ops2 = list(churn_scenario(0, n_nodes=50, n_events=600, ops_per_step=40))
    assert [(o.step, o.op, o.kind, o.name) for o in ops] == [
        (o.step, o.op, o.kind, o.name) for o in ops2
    ]


def test_churn_replay_end_to_end():
    runner = ScenarioRunner()
    res = runner.run(churn_scenario(3, n_nodes=30, n_events=400, ops_per_step=40))
    assert res.events_applied >= 400
    assert res.pods_scheduled > 100
    # The store stays consistent: every bound pod's node exists.
    nodes = {n["metadata"]["name"] for n in runner.store.list("nodes")}
    for p in runner.store.list("pods"):
        nn = p["spec"].get("nodeName")
        assert nn is None or nn in nodes


def test_churn_replay_deterministic():
    """Same seed -> identical placements and aggregates (the replayable-
    trace property the deterministic selectHost tiebreak exists for)."""
    def run_once():
        runner = ScenarioRunner()
        res = runner.run(churn_scenario(9, n_nodes=20, n_events=300, ops_per_step=30))
        bound = sorted(
            (p["metadata"]["name"], p["spec"].get("nodeName"))
            for p in runner.store.list("pods")
        )
        return res.pods_scheduled, res.unschedulable_attempts, bound

    assert run_once() == run_once()
