"""Streaming-windowed trace ingest (round 22, ksim_tpu/traces/stream).

The golden property everything here leans on: the windowed producer is
BYTE-IDENTICAL to the materialized pipeline — same selection
(StreamSelector == resample, any feed order), same compiled operation
sequence (window boundaries are invisible), same degraded output when a
producer fault reroutes through the materialized batch path.  Plus the
early-refusal satellite: an event/node bound provably blown mid-read
stops consuming the source instead of compiling it whole.
"""

from __future__ import annotations

import json
import random

import pytest

from ksim_tpu.traces import (
    StreamSelector,
    TraceBoundExceeded,
    TraceOperationStream,
    stream_trace_operations,
    trace_operations,
)
from ksim_tpu.traces.resample import resample
from ksim_tpu.traces.schema import TraceRecord

FIXTURES = "tests/fixtures/traces"


def _mk_records(n: int, seed: int) -> list[TraceRecord]:
    rng = random.Random(seed)
    return [
        TraceRecord(
            name=f"t{i}",
            arrival_s=round(rng.uniform(0, 1000), 3),
            cpu_milli=rng.randrange(100, 4000),
            mem_mib=rng.randrange(128, 8192),
            lifetime_s=rng.choice((0.0, round(rng.uniform(1, 500), 3))),
            tier=rng.randrange(5),
            priority=rng.randrange(450),
            kind=rng.choice(("batch", "service")),
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# StreamSelector == resample (order-independent selection)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "max_events,target_nodes,source_nodes",
    [
        (0, None, None),  # keep everything
        (60, None, None),  # budget only
        (60, 10, 40),  # budget + rescale
        (0, 10, 40),  # rescale only
    ],
)
def test_stream_selector_matches_resample_any_order(
    max_events, target_nodes, source_nodes
):
    """The selection rule is a pure function of the record SET: feeding
    the stream selector a shuffled permutation yields byte-identically
    what batch resample computes on the original order."""
    records = _mk_records(500, 3)
    batch = resample(
        records,
        seed=7,
        max_events=max_events,
        target_nodes=target_nodes,
        source_nodes=source_nodes,
    )
    shuffled = list(records)
    random.Random(99).shuffle(shuffled)
    sel = StreamSelector(
        seed=7,
        max_events=max_events,
        target_nodes=target_nodes,
        source_nodes=source_nodes,
    )
    sel.feed_all(shuffled)
    assert sel.finish() == batch


def test_stream_selector_heap_is_bounded_by_budget():
    """Budgeted mode holds at most B+1 candidates however long the
    stream runs — the O(window) memory claim's selection half."""
    sel = StreamSelector(seed=0, max_events=40)
    for rec in _mk_records(2000, 11):
        sel.feed(rec)
        assert len(sel._heap) <= 41
    assert sel.finish() == resample(_mk_records(2000, 11), seed=0, max_events=40)


# ---------------------------------------------------------------------------
# Windowed == materialized on the bundled fixtures
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "fname,fmt",
    [("borg_mini.jsonl", "borg"), ("alibaba_batch_mini.csv", "alibaba")],
)
@pytest.mark.parametrize("window", [1, 3, 64])
def test_windowed_stream_equals_materialized_fixture(fname, fmt, window):
    path = f"{FIXTURES}/{fname}"
    kw = dict(nodes=6, ops_per_step=3, max_events=30, seed=0)
    mat = trace_operations(path, fmt, **kw)
    stream = stream_trace_operations(
        path, fmt, window=window, queue_windows=2, **kw
    )
    assert list(stream) == mat
    stats = stream.stats()
    assert stats["fallback"] == 0
    assert stats["ops"] == len(mat)
    assert stats["windows"] == -(-len(mat) // window)  # ceil division


def test_window_boundary_splits_a_create_delete_pair():
    """A create and its delete landing in DIFFERENT windows must not
    perturb the stream — window boundaries are a transport detail, not
    a semantic one."""
    lines = []
    for i in range(6):
        lines.append(
            json.dumps(
                {
                    "time": i * 1_000_000,
                    "type": "SUBMIT",
                    "collection_id": i,
                    "instance_index": 0,
                    "priority": 0,
                    "resource_request": {"cpus": 0.01, "memory": 0.01},
                }
            )
        )
        lines.append(
            json.dumps(
                {
                    "time": i * 1_000_000 + 500_000,
                    "type": "FINISH",
                    "collection_id": i,
                    "instance_index": 0,
                }
            )
        )
    kw = dict(nodes=2, ops_per_step=2, seed=0)
    mat = trace_operations(lines, "borg", **kw)
    window = 3
    creates = [
        i for i, op in enumerate(mat) if op.kind == "pods" and op.op == "create"
    ]
    deletes = {
        op.name: i
        for i, op in enumerate(mat)
        if op.kind == "pods" and op.op == "delete"
    }
    split = [
        (i, deletes[mat[i].obj["metadata"]["name"]])
        for i in creates
        if mat[i].obj["metadata"]["name"] in deletes
        and i // window != deletes[mat[i].obj["metadata"]["name"]] // window
    ]
    assert split, "fixture must place some create/delete pair across a boundary"
    stream = stream_trace_operations(
        lines, "borg", window=window, queue_windows=2, **kw
    )
    assert list(stream) == mat


# ---------------------------------------------------------------------------
# Producer-fault degradation (the armed-chaos satellite)
# ---------------------------------------------------------------------------


def test_producer_fault_degrades_to_materialized_path():
    """An armed ``traces.stream`` fault fails the streaming ingest; the
    producer falls back to the materialized batch path, counts the
    degrade (stats + the ``traces.ingest_fallback`` event), and the
    operation sequence stays byte-identical."""
    from ksim_tpu.faults import FAULTS
    from ksim_tpu.obs import TRACE

    path = f"{FIXTURES}/borg_mini.jsonl"
    kw = dict(nodes=6, ops_per_step=3, seed=0)
    mat = trace_operations(path, "borg", **kw)
    FAULTS.reset()
    TRACE.reset()
    TRACE.enable(ring=True)
    try:
        FAULTS.arm("traces.stream", "always")
        stream = stream_trace_operations(
            path, "borg", window=4, queue_windows=2, **kw
        )
        assert list(stream) == mat
        assert stream.stats()["fallback"] == 1
        names = [r["name"] for r in TRACE.ring_records()]
        assert "traces.ingest_fallback" in names
    finally:
        FAULTS.reset()
        TRACE.reset()


# ---------------------------------------------------------------------------
# Early bound refusal (the KSIM_JOBS_MAX_* satellite)
# ---------------------------------------------------------------------------


def _borg_pair_lines(n: int) -> list[str]:
    out = []
    for i in range(n):
        out.append(
            json.dumps(
                {
                    "time": i * 1_000_000,
                    "type": "SUBMIT",
                    "collection_id": i,
                    "instance_index": 0,
                    "priority": 0,
                    "resource_request": {"cpus": 0.01, "memory": 0.01},
                }
            )
        )
        out.append(
            json.dumps(
                {
                    "time": i * 1_000_000 + 500_000,
                    "type": "FINISH",
                    "collection_id": i,
                    "instance_index": 0,
                }
            )
        )
    return out


def test_event_bound_refusal_stops_reading_the_source():
    """The bound trips mid-read: the refusal surfaces before the
    producer has consumed more than a small prefix of the source."""
    lines = _borg_pair_lines(200)
    consumed = []

    def counting():
        for line in lines:
            consumed.append(1)
            yield line

    stream = TraceOperationStream(
        counting(), "borg", nodes=4, ops_per_step=2, event_bound=20
    )
    with pytest.raises(TraceBoundExceeded, match="at least"):
        list(stream)
    assert 0 < len(consumed) < len(lines) // 2


def test_event_bound_refusal_before_reading_when_nodes_alone_blow_it():
    consumed = []

    def counting():
        for line in _borg_pair_lines(5):
            consumed.append(1)
            yield line

    with pytest.raises(TraceBoundExceeded, match="events"):
        TraceOperationStream(
            counting(), "borg", nodes=30, ops_per_step=2, event_bound=20
        )
    assert consumed == []


def test_node_bound_refuses_synchronously():
    with pytest.raises(TraceBoundExceeded, match="nodes"):
        TraceOperationStream(
            _borg_pair_lines(5), "borg", nodes=8, ops_per_step=2, node_bound=4
        )


# ---------------------------------------------------------------------------
# Stream object contract
# ---------------------------------------------------------------------------


def test_stream_close_is_idempotent_and_early():
    stream = stream_trace_operations(
        f"{FIXTURES}/borg_mini.jsonl", "borg", nodes=6, ops_per_step=3,
        window=1, queue_windows=1,
    )
    first = next(iter(stream))
    assert first.kind == "nodes"
    stream.close()
    stream.close()


def test_runner_refuses_streaming_off_the_solo_path():
    """Fleet replay, incremental resume, and checkpointing all need the
    materialized step-key index — each refuses a streaming source
    loudly instead of silently draining it."""
    from ksim_tpu.scenario import ScenarioRunner

    def fresh():
        return stream_trace_operations(
            f"{FIXTURES}/borg_mini.jsonl", "borg", nodes=6, ops_per_step=3
        )

    s = fresh()
    try:
        with pytest.raises(ValueError, match="solo-run path"):
            ScenarioRunner(device_replay=True, fleet=2).run(s)
        with pytest.raises(ValueError, match="resume"):
            ScenarioRunner().run(fresh(), resume_cursor=3)
        with pytest.raises(ValueError, match="checkpoint_hook"):
            ScenarioRunner(
                device_replay=True, checkpoint_hook=lambda *a: None
            ).run(fresh())
        with pytest.raises(ValueError, match="materialized"):
            ScenarioRunner(device_replay=True, fleet=2).run(
                [], lane_ops={0: fresh()}
            )
    finally:
        s.close()
