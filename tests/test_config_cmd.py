"""Config layering (env over yaml) and the two process entrypoints."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from ksim_tpu.config import load_config
from ksim_tpu.errors import InvalidConfigError
from tests.helpers import make_node, make_pod, sanitized_cpu_env

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def clean_env(monkeypatch):
    for k in (
        "PORT",
        "CORS_ALLOWED_ORIGIN_LIST",
        "KUBE_SCHEDULER_CONFIG_PATH",
        "EXTERNAL_IMPORT_ENABLED",
        "RESOURCE_SYNC_ENABLED",
        "EXTERNAL_SNAPSHOT_PATH",
        "KUBE_CONFIG",
        "KUBECONFIG",
    ):
        monkeypatch.delenv(k, raising=False)
    return monkeypatch


def test_yaml_plus_env_layering(tmp_path, clean_env):
    sched = tmp_path / "scheduler.yaml"
    sched.write_text("profiles:\n- schedulerName: my-sched\n")
    cfg_file = tmp_path / "config.yaml"
    cfg_file.write_text(
        "apiVersion: kube-scheduler-simulator-config/v1alpha1\n"
        "kind: SimulatorConfiguration\n"
        "port: 3131\n"
        "corsAllowedOriginList:\n- http://localhost:3000\n"
        f"kubeSchedulerConfigPath: {sched}\n"
        "etcdURL: http://ignored:2379\n"  # KWOK-topology field: ignored
    )
    cfg = load_config(str(cfg_file))
    assert cfg.port == 3131
    assert cfg.cors_allowed_origin_list == ("http://localhost:3000",)
    assert cfg.initial_scheduler_cfg["profiles"][0]["schedulerName"] == "my-sched"
    # Env overrides yaml (reference getPort: PORT first).
    clean_env.setenv("PORT", "4545")
    assert load_config(str(cfg_file)).port == 4545


def test_import_modes_mutually_exclusive(tmp_path, clean_env):
    cfg_file = tmp_path / "config.yaml"
    cfg_file.write_text(
        "port: 1212\nexternalImportEnabled: true\nresourceSyncEnabled: true\n"
        "externalSnapshotPath: /tmp/x.json\n"
    )
    with pytest.raises(InvalidConfigError):
        load_config(str(cfg_file))
    cfg_file.write_text("port: 1212\nexternalImportEnabled: true\n")
    with pytest.raises(InvalidConfigError):
        load_config(str(cfg_file))  # import without a source
    # kubeConfig is an alternative source (reference config.go:88-114)...
    cfg_file.write_text(
        "port: 1212\nresourceSyncEnabled: true\nkubeConfig: /tmp/kc.yaml\n"
    )
    assert load_config(str(cfg_file)).kube_config == "/tmp/kc.yaml"
    # The reference's KUBECONFIG env var works as a fallback source...
    clean_env.setenv("KUBECONFIG", "/tmp/ambient-kc.yaml")
    cfg_file.write_text("port: 1212\nresourceSyncEnabled: true\n")
    assert load_config(str(cfg_file)).kube_config == "/tmp/ambient-kc.yaml"
    # ...but never conflicts with an explicitly configured snapshot path.
    cfg_file.write_text(
        "port: 1212\nexternalImportEnabled: true\n"
        "externalSnapshotPath: /tmp/x.json\n"
    )
    cfg = load_config(str(cfg_file))
    assert cfg.external_snapshot_path == "/tmp/x.json" and not cfg.kube_config
    clean_env.delenv("KUBECONFIG")
    # ...but not alongside a snapshot file.
    cfg_file.write_text(
        "port: 1212\nexternalImportEnabled: true\nkubeConfig: /tmp/kc.yaml\n"
        "externalSnapshotPath: /tmp/x.json\n"
    )
    with pytest.raises(InvalidConfigError):
        load_config(str(cfg_file))


def _run_cmd(args, timeout=120):
    # CPU is plenty for entrypoint smoke tests; sanitized_cpu_env keeps the
    # subprocess off the TPU plugin path so a wedged chip can't hang it.
    return subprocess.run(
        [sys.executable, "-m", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )


def test_scheduler_entrypoint_schedules_snapshot(tmp_path):
    snap = {
        "nodes": [make_node("n0", cpu="4", memory="8Gi")],
        "pods": [make_pod("p0", cpu="1", memory="1Gi")],
        "pvs": [], "pvcs": [], "storageClasses": [], "priorityClasses": [],
        "namespaces": [], "schedulerConfig": None,
    }
    snap_file = tmp_path / "snap.json"
    snap_file.write_text(json.dumps(snap))
    out_file = tmp_path / "out.json"
    proc = _run_cmd(
        ["ksim_tpu.cmd.scheduler", "--snapshot", str(snap_file), "--out", str(out_file)]
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(out_file.read_text())
    assert result["pods"][0]["spec"]["nodeName"] == "n0"
    anno = result["pods"][0]["metadata"]["annotations"]
    assert anno["kube-scheduler-simulator.sigs.k8s.io/selected-node"] == "n0"


def test_config_write_back(tmp_path):
    """Applying a config persists it to the configured scheduler.yaml
    (the reference's UpdateSchedulerConfig rewrite)."""
    import yaml

    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore

    path = tmp_path / "scheduler.yaml"
    svc = SchedulerService(ClusterStore(), config_path=str(path))
    svc.apply_scheduler_config({"profiles": [{"schedulerName": "x"}]})
    assert yaml.safe_load(path.read_text())["profiles"] == [{"schedulerName": "x"}]
