"""Concurrency stress: hammer the store + scheduler + watch + reset from
many threads and assert invariants.

The reference's race story is mutexes + conflict retries with no race
tests at all (SURVEY.md §5: `go test ./...` without -race).  This tier
drives every shared structure concurrently — CRUD writers, watch
consumers, the scheduling loop, resets — and asserts nothing corrupts:
no unexpected exceptions, watch streams see a consistent event order,
and the store's sorted index stays exact under interleaved membership
churn.
"""

from __future__ import annotations

import json
import random
import threading
import time

from ksim_tpu.errors import SimulatorError
from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.state.cluster import DELETED, ClusterStore
from tests.helpers import make_node, make_pod


def _run_threads(workers, duration=4.0):
    """Run worker(stop_event) callables concurrently; collect errors."""
    stop = threading.Event()
    errors: list[BaseException] = []
    lock = threading.Lock()

    def wrap(fn):
        def run():
            try:
                fn(stop)
            except BaseException as e:  # noqa: BLE001 - collected for assert
                with lock:
                    errors.append(e)

        return run

    threads = [threading.Thread(target=wrap(w), daemon=True) for w in workers]
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    return errors


def test_store_crud_watch_reset_hammer():
    """Interleaved create/update/delete/list/watch/restore across threads:
    no exceptions beyond expected conflicts, and the sorted list order
    stays exactly name-sorted afterwards."""
    # strict=True: sanitizer-lite mode (docs/lint.md) — every internal
    # mutator asserts the store lock is held, so a locking regression
    # fails LOUDLY here instead of as a once-in-a-thousand-runs index
    # corruption.
    store = ClusterStore(strict=True)
    for i in range(20):
        store.create("nodes", make_node(f"seed-{i:02d}"))
    boot = store.dump()

    def writer(stop):
        rng = random.Random(threading.get_ident())
        n = 0
        while not stop.is_set():
            name = f"w{threading.get_ident() % 997}-{n % 50}"
            n += 1
            try:
                store.create("pods", make_pod(name))
            except SimulatorError:
                try:
                    store.delete("pods", name, "default")
                except SimulatorError:
                    pass
            if rng.random() < 0.3:
                try:
                    store.patch(
                        "pods", name, "default",
                        lambda o: o["metadata"].setdefault("labels", {}).update(x="y"),
                    )
                except SimulatorError:
                    pass

    def lister(stop):
        while not stop.is_set():
            pods = store.list("pods", copy_objs=False)
            names = [p["metadata"]["name"] for p in pods]
            assert names == sorted(names), "sorted index corrupted"
            store.list("nodes")

    def watcher(stop):
        stream = store.watch(("pods",))
        try:
            while not stop.is_set():
                ev = stream.next(timeout=0.05)
                if ev is not None:
                    assert ev.kind == "pods"
                    json.dumps(ev.to_json())  # serializable under churn
        finally:
            stream.close()

    def resetter(stop):
        while not stop.is_set():
            time.sleep(0.7)
            store.restore(boot)

    errors = _run_threads([writer, writer, lister, watcher, resetter])
    assert not errors, errors
    # Final invariant: index matches table exactly, in name order.
    for kind in ("pods", "nodes"):
        objs = store.list(kind, copy_objs=False)
        assert len(objs) == len(store._objects[kind])
        names = [o["metadata"]["name"] for o in objs]
        assert names == sorted(names)


def test_scheduler_under_concurrent_churn():
    """The watch-driven scheduler stays consistent while other threads
    churn pods/nodes: every bound pod points at an existing node or a
    node that was deleted after binding; the loop survives to the end."""
    store = ClusterStore(strict=True)  # lock-held asserts on (docs/lint.md)
    for i in range(6):
        store.create("nodes", make_node(f"n{i}", cpu="8", memory="16Gi"))
    svc = SchedulerService(store, record="selection", preemption=False)
    svc.start()
    deleted_nodes: set[str] = set()
    lock = threading.Lock()

    def pod_churner(stop):
        rng = random.Random(1)
        n = 0
        while not stop.is_set():
            try:
                store.create("pods", make_pod(f"c{n}", cpu="100m"))
            except SimulatorError:
                pass
            n += 1
            if rng.random() < 0.4 and n > 3:
                try:
                    store.delete("pods", f"c{rng.randrange(n)}", "default")
                except SimulatorError:
                    pass
            time.sleep(0.01)

    def node_churner(stop):
        i = 6
        while not stop.is_set():
            time.sleep(0.5)
            try:
                with lock:
                    deleted_nodes.add(f"n{i - 6}")
                store.delete("nodes", f"n{i - 6}")
            except SimulatorError:
                pass
            store.create("nodes", make_node(f"n{i}", cpu="8", memory="16Gi"))
            i += 1

    try:
        errors = _run_threads([pod_churner, node_churner], duration=5.0)
        assert not errors, errors
        # Let the loop quiesce, then check the binding invariant.
        time.sleep(2.0)
        node_names = {n["metadata"]["name"] for n in store.list("nodes")}
        with lock:
            ok_targets = node_names | deleted_nodes
        for p in store.list("pods"):
            nn = p["spec"].get("nodeName")
            assert nn is None or nn in ok_targets, f"pod bound to unknown node {nn}"
    finally:
        # A loop thread still mid-XLA-compile at interpreter exit can
        # corrupt the heap during runtime teardown (observed once, cold
        # cache): join it for real before pytest exits.
        svc.stop(timeout=None)
