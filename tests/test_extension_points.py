"""Out-of-tree extension points beyond filter/score/permit.

The reference wraps and exposes every framework extension point for
out-of-tree plugins: custom QueueSort (wrappedplugin.go:750-765),
PreEnqueue (:376), PostFilter (:550-577), Bind/PostBind (:699-748), and
Before/After extender interfaces (:47-171).  These tests register
equivalents through the Builder registry / ``builderImport`` and drive
the SchedulerService end to end.
"""

from __future__ import annotations

import json

import pytest

from ksim_tpu.engine.annotations import (
    BIND_RESULT_KEY,
    PERMIT_RESULT_KEY,
    POST_FILTER_RESULT_KEY,
    PRE_BIND_RESULT_KEY,
)
from ksim_tpu.engine.core import PluginExtender, ScoredPlugin
from ksim_tpu.plugins.samples.lifecycle import PlacementExport
from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.scheduler.profile import compile_profile
from ksim_tpu.state.cluster import ClusterStore
from tests.helpers import make_node, make_pod


def _store(*objs):
    store = ClusterStore()
    for kind, obj in objs:
        store.create(kind, obj)
    return store


def _marker(name_, **hooks):
    cls = type("_Marker", (), {"name": name_, **hooks})
    return cls()


def test_custom_queue_sort_changes_scheduling_order():
    """FifoSort (creation-time order) vs PrioritySort (priority first):
    with room for only one pod, the custom order decides which binds."""
    node = make_node("n1", pods=1)
    early_low = make_pod("early-low")
    early_low["metadata"]["creationTimestamp"] = "2024-01-01T00:00:00Z"
    late_high = make_pod("late-high", priority=100)
    late_high["metadata"]["creationTimestamp"] = "2024-01-02T00:00:00Z"

    def run(config):
        store = _store(
            ("nodes", node), ("pods", early_low), ("pods", late_high)
        )
        svc = SchedulerService(
            store, config=config, preemption=False, allow_plugin_imports=True
        )
        return svc.schedule_pending()

    default = run({})
    assert default["default/late-high"] == "n1"
    assert default["default/early-low"] is None

    fifo_cfg = {
        "profiles": [
            {
                "plugins": {"queueSort": {"enabled": [{"name": "FifoSort"}]}},
                "pluginConfig": [
                    {
                        "name": "FifoSort",
                        "args": {
                            "builderImport": "ksim_tpu.plugins.samples.lifecycle:FIFO_SORT_PLUGIN"
                        },
                    }
                ],
            }
        ]
    }
    fifo = run(fifo_cfg)
    assert fifo["default/early-low"] == "n1"
    assert fifo["default/late-high"] is None


def test_two_queue_sorters_rejected():
    with pytest.raises(ValueError, match="multiple queue-sort"):
        compile_profile(
            {
                "plugins": {
                    "queueSort": {
                        "enabled": [{"name": "SortA"}, {"name": "SortB"}]
                    }
                }
            },
            registry={
                "SortA": {
                    "builder": lambda f, a: ScoredPlugin(_marker("SortA")),
                    "queue_sort_key": lambda p, pr=None: name_of_key(p),
                },
                "SortB": {
                    "builder": lambda f, a: ScoredPlugin(_marker("SortB")),
                    "queue_sort_key": lambda p, pr=None: name_of_key(p),
                },
            },
        )


def name_of_key(p):
    return p.get("metadata", {}).get("name", "")


def test_pre_enqueue_gate_keeps_pod_out_of_queue():
    store = _store(
        ("nodes", make_node("n1")),
        ("pods", make_pod("hold-me")),
        ("pods", make_pod("free")),
    )
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {
                    "plugins": {
                        "preEnqueue": {"enabled": [{"name": "NamePrefixGate"}]}
                    },
                    "pluginConfig": [
                        {
                            "name": "NamePrefixGate",
                            "args": {
                                "builderImport": "ksim_tpu.plugins.samples.lifecycle:NAME_PREFIX_GATE_PLUGIN"
                            },
                        }
                    ],
                }
            ]
        },
        allow_plugin_imports=True,
    )
    placements = svc.schedule_pending()
    assert placements == {"default/free": "n1"}
    held = store.get("pods", "hold-me")
    assert not held.get("spec", {}).get("nodeName")


def test_post_bind_plugin_observes_binds(tmp_path):
    records = []

    def build(feats, args):
        return ScoredPlugin(
            PlacementExport(
                sink=records.append, sink_path=str(tmp_path / "binds.jsonl")
            ),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {
                    "plugins": {
                        "postBind": {"enabled": [{"name": "PlacementExport"}]}
                    }
                }
            ]
        },
        registry={"PlacementExport": build},
    )
    assert svc.schedule_pending() == {"default/p1": "n1"}
    assert records == [{"pod": "default/p1", "node": "n1"}]
    lines = (tmp_path / "binds.jsonl").read_text().splitlines()
    assert json.loads(lines[0]) == {"pod": "default/p1", "node": "n1"}


def test_custom_post_filter_nominates_node():
    """With no feasible node and nothing to preempt, a custom PostFilter
    hook nominates — recorded in postfilter-result and the pod's status
    (upstream RunPostFilterPlugins first-success)."""

    def build(feats, args):
        def post_filter(pod, failed_nodes):
            return failed_nodes[0]

        return ScoredPlugin(
            _marker("Nominator", post_filter=staticmethod(post_filter)),
            filter_enabled=False,
            score_enabled=False,
        )

    node = make_node("n1", pods=0)  # every pod fails "Too many pods"
    store = _store(("nodes", node), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"postFilter": {"enabled": [{"name": "Nominator"}]}}}
            ]
        },
        registry={"Nominator": build},
    )
    placements = svc.schedule_pending()
    assert placements == {"default/p1": None}
    pod = store.get("pods", "p1")
    assert pod["status"].get("nominatedNodeName") == "n1"
    post = json.loads(pod["metadata"]["annotations"][POST_FILTER_RESULT_KEY])
    assert post["n1"] == {"Nominator": "preemption victim"}


def test_pre_bind_failure_fails_the_cycle():
    def build(feats, args):
        def pre_bind(pod, node_name):
            return "volume attach failed"

        return ScoredPlugin(
            _marker("Attacher", pre_bind=staticmethod(pre_bind)),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"preBind": {"enabled": [{"name": "Attacher"}]}}}
            ]
        },
        registry={"Attacher": build},
    )
    placements = svc.schedule_pending()
    assert placements == {"default/p1": None}
    pod = store.get("pods", "p1")
    assert not pod.get("spec", {}).get("nodeName")
    prebind = json.loads(pod["metadata"]["annotations"][PRE_BIND_RESULT_KEY])
    assert prebind["Attacher"] == "volume attach failed"


def test_custom_bind_plugin_records_under_its_name():
    def build(feats, args):
        def bind(pod, node_name):
            return True

        return ScoredPlugin(
            _marker("CustomBinder", bind=staticmethod(bind)),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"bind": {"enabled": [{"name": "CustomBinder"}]}}}
            ]
        },
        registry={"CustomBinder": build},
    )
    assert svc.schedule_pending() == {"default/p1": "n1"}
    pod = store.get("pods", "p1")
    assert pod["spec"]["nodeName"] == "n1"
    bind_map = json.loads(pod["metadata"]["annotations"][BIND_RESULT_KEY])
    assert bind_map == {"CustomBinder": "success"}


def test_bind_skip_falls_through_to_default_binder():
    def build(feats, args):
        def bind(pod, node_name):
            return None  # Skip

        return ScoredPlugin(
            _marker("SkipBinder", bind=staticmethod(bind)),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"bind": {"enabled": [{"name": "SkipBinder"}]}}}
            ]
        },
        registry={"SkipBinder": build},
    )
    assert svc.schedule_pending() == {"default/p1": "n1"}
    pod = store.get("pods", "p1")
    bind_map = json.loads(pod["metadata"]["annotations"][BIND_RESULT_KEY])
    assert bind_map == {"DefaultBinder": "success"}


def test_permit_extender_before_rejects():
    """A BeforePermit non-success skips the original hook and rejects
    (extender ifaces, wrappedplugin.go:47-171)."""

    calls = []

    def build(feats, args):
        def permit(pod, node_name):
            calls.append("original")
            from ksim_tpu.scheduler.permit import PermitResult

            return PermitResult.allow()

        ext = PluginExtender(
            before_permit=lambda pod, node: "blocked by extender"
        )
        return ScoredPlugin(
            _marker("Guard", permit=staticmethod(permit)),
            filter_enabled=False,
            score_enabled=False,
            extender=ext,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"permit": {"enabled": [{"name": "Guard"}]}}}
            ]
        },
        registry={"Guard": build},
    )
    placements = svc.schedule_pending()
    assert placements == {"default/p1": None}
    assert calls == []  # original permit skipped
    pod = store.get("pods", "p1")
    permit_map = json.loads(pod["metadata"]["annotations"][PERMIT_RESULT_KEY])
    assert permit_map == {"Guard": "blocked by extender"}


def test_post_bind_runs_after_permit_allow():
    """A Permit-WAIT pod that is later allowed still runs the
    PreBind/Bind/PostBind chains at allow time."""
    from ksim_tpu.scheduler.permit import PermitResult

    records = []

    def build(feats, args):
        def permit(pod, node_name):
            return PermitResult.wait(60)

        return ScoredPlugin(
            _marker(
                "WaitThenExport",
                permit=staticmethod(permit),
                post_bind=staticmethod(
                    lambda pod, node: records.append(node)
                ),
            ),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {
                    "plugins": {
                        "permit": {"enabled": [{"name": "WaitThenExport"}]},
                        "postBind": {"enabled": [{"name": "WaitThenExport"}]},
                    }
                }
            ]
        },
        registry={"WaitThenExport": build},
    )
    placements = svc.schedule_pending()
    assert placements == {"default/p1": "n1"}  # assumed node while waiting
    assert records == []
    assert svc.allow_waiting_pod("p1")
    pod = store.get("pods", "p1")
    assert pod["spec"]["nodeName"] == "n1"
    assert records == ["n1"]


def test_pod_deleted_mid_pass_skips_only_that_bind():
    """A pod deleted while the pass runs (reset/external delete during a
    long compile — surfaced by a live-server drive in round 4) fails only
    its own bind; the rest of the batch still binds."""
    from ksim_tpu.scheduler.permit import PermitResult

    store = _store(
        ("nodes", make_node("n1")),
        ("pods", make_pod("doomed")),
        ("pods", make_pod("survivor")),
    )

    def build(feats, args):
        def permit(pod, node_name):
            # Runs inside _bind_results before the store write — the
            # realistic shape of "deleted mid-pass".
            if pod["metadata"]["name"] == "doomed":
                store.delete("pods", "doomed")
            return PermitResult.allow()

        return ScoredPlugin(
            _marker("Deleter", permit=staticmethod(permit)),
            filter_enabled=False,
            score_enabled=False,
        )

    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"permit": {"enabled": [{"name": "Deleter"}]}}}
            ]
        },
        registry={"Deleter": build},
    )
    placements = svc.schedule_pending()
    assert placements.get("default/survivor") == "n1"
    assert "default/doomed" not in placements
    assert store.get("pods", "survivor")["spec"]["nodeName"] == "n1"


def test_point_only_plugin_does_not_run_hooks_at_other_points():
    """A plugin enabled only at the score point must NOT have its
    pre_bind hook invoked (upstream never calls a plugin at a point the
    config didn't enable it at)."""
    calls = []

    def build(feats, args):
        import jax.numpy as jnp

        def score(self, state, pod, aux, ok=None):
            return jnp.zeros(state.valid.shape[0], dtype=jnp.int32)

        marker = _marker(
            "ScoreOnly",
            score=score,
            pre_bind=staticmethod(
                lambda pod, node: calls.append((pod["metadata"]["name"], node))
                or "should never run"
            ),
        )
        return ScoredPlugin(marker, filter_enabled=False)

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"score": {"enabled": [{"name": "ScoreOnly"}]}}}
            ]
        },
        registry={"ScoreOnly": build},
    )
    assert svc.schedule_pending() == {"default/p1": "n1"}
    assert calls == []


def test_reserve_and_unreserve_hooks():
    """Reserve runs before Permit on the selected node; a Reserve failure
    unreserves (reverse order) and fails the cycle with the message
    recorded (upstream RunReservePlugins, wrappedplugin.go:616-668)."""
    from ksim_tpu.engine.annotations import RESERVE_RESULT_KEY

    events = []

    def build_ok(feats, args):
        return ScoredPlugin(
            _marker(
                "Claimer",
                reserve=staticmethod(
                    lambda pod, node: events.append(("reserve", node)) and None
                ),
                unreserve=staticmethod(
                    lambda pod, node: events.append(("unreserve", node))
                ),
            ),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"reserve": {"enabled": [{"name": "Claimer"}]}}}
            ]
        },
        registry={"Claimer": build_ok},
    )
    assert svc.schedule_pending() == {"default/p1": "n1"}
    assert events == [("reserve", "n1")]  # success: no unreserve
    pod = store.get("pods", "p1")
    reserve = json.loads(pod["metadata"]["annotations"][RESERVE_RESULT_KEY])
    assert reserve["Claimer"] == "success"

    # Failure path: reserve rejects -> unreserve runs, pod stays pending.
    events.clear()

    def build_fail(feats, args):
        return ScoredPlugin(
            _marker(
                "Claimer",
                reserve=staticmethod(lambda pod, node: "quota exhausted"),
                unreserve=staticmethod(
                    lambda pod, node: events.append(("unreserve", node))
                ),
            ),
            filter_enabled=False,
            score_enabled=False,
        )

    store2 = _store(("nodes", make_node("n1")), ("pods", make_pod("p2")))
    svc2 = SchedulerService(
        store2,
        config={
            "profiles": [
                {"plugins": {"reserve": {"enabled": [{"name": "Claimer"}]}}}
            ]
        },
        registry={"Claimer": build_fail},
    )
    assert svc2.schedule_pending() == {"default/p2": None}
    assert events == [("unreserve", "n1")]
    pod2 = store2.get("pods", "p2")
    assert not pod2.get("spec", {}).get("nodeName")
    reserve2 = json.loads(pod2["metadata"]["annotations"][RESERVE_RESULT_KEY])
    assert reserve2["Claimer"] == "quota exhausted"


def test_unreserve_runs_on_permit_rejection():
    from ksim_tpu.scheduler.permit import PermitResult

    events = []

    def build(feats, args):
        return ScoredPlugin(
            _marker(
                "Guard",
                reserve=staticmethod(lambda pod, node: None),
                unreserve=staticmethod(
                    lambda pod, node: events.append("unreserve")
                ),
                permit=staticmethod(
                    lambda pod, node: PermitResult.reject("not today")
                ),
            ),
            filter_enabled=False,
            score_enabled=False,
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {
                    "plugins": {
                        "reserve": {"enabled": [{"name": "Guard"}]},
                        "permit": {"enabled": [{"name": "Guard"}]},
                    }
                }
            ]
        },
        registry={"Guard": build},
    )
    assert svc.schedule_pending() == {"default/p1": None}
    assert events == ["unreserve"]


def test_normalize_extender_rescales_scores():
    """The NormalizeScore extender pair wraps a plugin's normalize
    inside the compiled program (wrappedplugin.go:388-418): after_
    normalize halves NodeAffinity's normalized scores before weighting."""
    import jax.numpy as jnp

    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer

    nodes = [
        make_node("n-a", labels={"zone": "a"}),
        make_node("n-b", labels={"zone": "b"}),
    ]
    pod = make_pod(
        "p",
        affinity={
            "nodeAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 10,
                        "preference": {
                            "matchExpressions": [
                                {"key": "zone", "operator": "In", "values": ["a"]}
                            ]
                        },
                    }
                ]
            }
        },
    )
    feats = Featurizer().featurize(nodes, [], queue_pods=[pod])
    ext = PluginExtender(
        after_normalize=lambda state, p, aux, norm, ok: norm // 2
    )
    base = default_plugins(feats)
    wrapped = tuple(
        ScoredPlugin(
            sp.plugin, weight=sp.weight, filter_enabled=sp.filter_enabled,
            score_enabled=sp.score_enabled,
            extender=ext if sp.plugin.name == "NodeAffinity" else sp.extender,
        )
        for sp in base
    )
    plain = Engine(feats, base, record="full").evaluate_batch()
    halved = Engine(feats, wrapped, record="full").evaluate_batch()
    si = plain.plugin_names.index("NodeAffinity")
    # Normalized score on n-a is 100 (weight 2 -> final 200); halved -> 50*2.
    assert int(plain.final_scores[0, si, 0]) == 200
    assert int(halved.final_scores[0, si, 0]) == 100


def test_extender_only_host_plugin_is_retained():
    """A plugin whose only host surface is an extender pair (no method
    on the plugin object) must stay in the compiled plugin set — the
    wrapped plugin always exists upstream and the extender runs around
    the nil original."""
    calls = []

    def build(feats, args):
        return ScoredPlugin(
            _marker("ExtOnly"),
            filter_enabled=False,
            score_enabled=False,
            extender=PluginExtender(
                before_permit=lambda pod, node: calls.append(node) and None
            ),
        )

    store = _store(("nodes", make_node("n1")), ("pods", make_pod("p1")))
    svc = SchedulerService(
        store,
        config={
            "profiles": [
                {"plugins": {"permit": {"enabled": [{"name": "ExtOnly"}]}}}
            ]
        },
        registry={"ExtOnly": build},
    )
    assert svc.schedule_pending() == {"default/p1": "n1"}
    assert calls == ["n1"]  # extender ran around the nil original permit
