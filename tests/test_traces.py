"""Trace ingestion plane (ksim_tpu/traces): parsers, resampling,
compilation, the name registry, and the scenario-spec wiring.

Golden expectations here are HAND-DERIVED from the documented format
subsets (fixture rule, repo CLAUDE.md): e.g. a Borg ``cpus`` of 0.05
against the 16-core reference machine is 800 millicores BY ARITHMETIC,
never by running the parser and copying its output.  The replay-side
behavior lock for the bundled fixture lives in
tests/test_behavior_locks.py.
"""

from __future__ import annotations

import gzip
import json

import pytest

from ksim_tpu.traces import (
    PRIORITY_LADDER,
    TraceError,
    TraceParseError,
    TraceRecord,
    compile_trace,
    parse_alibaba,
    parse_borg,
    resample,
)
from ksim_tpu.traces.registry import list_traces, open_trace_lines, resolve

FIXTURES = "tests/fixtures/traces"


# ---------------------------------------------------------------------------
# Borg parser goldens (hand-derived from the documented subset)
# ---------------------------------------------------------------------------


def _borg_line(time_us, etype, cid, idx, prio=None, cpus=None, mem=None):
    o = {"time": time_us, "type": etype, "collection_id": cid, "instance_index": idx}
    if prio is not None:
        o["priority"] = prio
    if cpus is not None:
        o["resource_request"] = {"cpus": cpus, "memory": mem}
    return json.dumps(o)


def test_borg_golden_submit_finish_pair():
    lines = [
        _borg_line(2_000_000, 0, 7, 3, prio=200, cpus=0.05, mem=0.02),
        _borg_line(9_500_000, 6, 7, 3),  # FINISH
    ]
    (rec,) = list(parse_borg(lines))
    # Hand-derived: 0.05 * 16000 = 800 m; 0.02 * 65536 = 1310.72 -> 1311;
    # arrival 2.0 s; lifetime 9.5 - 2.0 = 7.5 s; priority 200 is the
    # production band -> tier 3 -> kind "service".
    assert rec == TraceRecord(
        name="c7-i3",
        arrival_s=2.0,
        cpu_milli=800,
        mem_mib=1311,
        lifetime_s=7.5,
        tier=3,
        priority=200,
        kind="service",
    )


def test_borg_tier_bands_and_string_types():
    """The published 0..450 bands map to tiers 0..4; type names and
    numbers are interchangeable."""
    lines = [
        _borg_line(0, "SUBMIT", 1, 0, prio=0),
        _borg_line(0, "SUBMIT", 1, 1, prio=103),
        _borg_line(0, "SUBMIT", 1, 2, prio=117),
        _borg_line(0, "SUBMIT", 1, 3, prio=200),
        _borg_line(0, "SUBMIT", 1, 4, prio=450),
    ]
    recs = {r.name: r for r in parse_borg(lines)}
    assert [recs[f"c1-i{i}"].tier for i in range(5)] == [0, 1, 2, 3, 4]
    assert recs["c1-i0"].kind == "batch" and recs["c1-i4"].kind == "service"
    # Missing resource_request parses as a zero request, not an error.
    assert recs["c1-i0"].cpu_milli == 0 and recs["c1-i0"].mem_mib == 0


def test_borg_lifecycle_noise_and_unmatched_terminals_ignored():
    lines = [
        _borg_line(1_000_000, "SUBMIT", 1, 0, prio=0),
        _borg_line(1_100_000, "QUEUE", 1, 0),
        _borg_line(1_200_000, "SCHEDULE", 1, 0),
        _borg_line(1_300_000, "FINISH", 9, 9),  # never submitted: ignored
        _borg_line(1_400_000, "SUBMIT", 1, 0, prio=0),  # duplicate live submit
        _borg_line(2_000_000, "KILL", 1, 0),
    ]
    (rec,) = list(parse_borg(lines))
    assert rec.name == "c1-i0" and rec.lifetime_s == 1.0


def test_borg_resubmit_opens_distinct_record():
    """A SUBMIT after a terminal is a NEW workload item with a distinct
    name (simulator pod names must never be reused — replay contract)."""
    lines = [
        _borg_line(1_000_000, "SUBMIT", 3, 1, prio=100),
        _borg_line(2_000_000, "EVICT", 3, 1),
        _borg_line(3_000_000, "SUBMIT", 3, 1, prio=100),
        _borg_line(5_000_000, "FINISH", 3, 1),
    ]
    recs = list(parse_borg(lines))
    assert [(r.name, r.arrival_s, r.lifetime_s) for r in recs] == [
        ("c3-i1", 1.0, 1.0),
        ("c3-i1-r1", 3.0, 2.0),
    ]


def test_borg_live_at_eof_has_no_lifetime():
    (rec,) = list(parse_borg([_borg_line(4_000_000, 0, 2, 0, prio=0)]))
    assert rec.lifetime_s == 0.0


def test_borg_malformed_rows_raise_with_line_numbers():
    good = _borg_line(0, 0, 1, 0, prio=0)
    with pytest.raises(TraceParseError, match="line 2: not valid JSON"):
        list(parse_borg([good, "{broken"]))
    with pytest.raises(TraceParseError, match="line 1: .*collection_id"):
        list(parse_borg(['{"time": 1, "type": 0, "instance_index": 0}']))
    with pytest.raises(TraceParseError, match="line 1: .*time"):
        list(parse_borg(['{"type": 0, "collection_id": 1, "instance_index": 0}']))
    with pytest.raises(TraceParseError, match="line 1"):
        list(parse_borg(['["an", "array"]']))


# ---------------------------------------------------------------------------
# Alibaba parser goldens
# ---------------------------------------------------------------------------


def test_alibaba_batch_task_golden():
    row = "M1,1,j_42,2,Terminated,100,160,300,2.5"
    (rec,) = list(parse_alibaba([row]))
    # Hand-derived: plan_cpu 300 centi-cores = 3000 m; plan_mem 2.5% of
    # the 64-GiB reference = 0.025 * 65536 = 1638.4 -> 1638; lifetime
    # 160 - 100 = 60 s; batch tier 1; task_type 2 kept as priority.
    assert rec == TraceRecord(
        name="j_42-M1",
        arrival_s=100.0,
        cpu_milli=3000,
        mem_mib=1638,
        lifetime_s=60.0,
        tier=1,
        priority=2,
        kind="batch",
    )


def test_alibaba_batch_empty_end_time_means_no_delete():
    (rec,) = list(parse_alibaba(["M1,1,j_1,1,Running,100,,100,0.8"]))
    assert rec.lifetime_s == 0.0


def test_alibaba_container_meta_golden_and_dedup():
    rows = [
        "c_1001,m_1,50,app_7,started,400,800,1.5625",
        "c_1001,m_1,60,app_7,started,400,800,1.5625",  # update row: ignored
        "c_1002,m_2,55,app_8,started,800,800,3.125",
    ]
    recs = list(parse_alibaba(rows))
    # Hand-derived: cpu_request 400 centi-cores = 4000 m; mem_size
    # 1.5625% of 65536 = 1024 MiB exactly; containers are service tier 3.
    assert [(r.name, r.arrival_s, r.cpu_milli, r.mem_mib) for r in recs] == [
        ("c_1001", 50.0, 4000, 1024),
        ("c_1002", 55.0, 8000, 2048),
    ]
    assert all(r.kind == "service" and r.tier == 3 and r.lifetime_s == 0 for r in recs)


def test_alibaba_malformed_rows_raise():
    with pytest.raises(TraceParseError, match="line 1: unrecognized table shape"):
        list(parse_alibaba(["a,b,c"]))
    with pytest.raises(TraceParseError, match="line 2: expected 9 columns"):
        list(parse_alibaba(["M1,1,j_1,1,T,100,160,300,2.5", "M2,1,j_1,1,T,100,160"]))
    with pytest.raises(TraceParseError, match="non-numeric"):
        list(parse_alibaba(["M1,1,j_1,1,T,abc,160,300,2.5"]))
    with pytest.raises(TraceParseError, match="empty required"):
        list(parse_alibaba(["M1,1,j_1,1,T,,160,300,2.5"]))


# ---------------------------------------------------------------------------
# IO: gz transparency, truncation, byte bound
# ---------------------------------------------------------------------------


def test_gz_input_parses_identically(tmp_path):
    plain = tmp_path / "t.jsonl"
    plain.write_text(
        _borg_line(1_000_000, 0, 1, 0, prio=100, cpus=0.05, mem=0.02)
        + "\n"
        + _borg_line(2_000_000, 6, 1, 0)
        + "\n"
    )
    # Deliberately NOT named .gz: detection is by magic bytes.
    gz = tmp_path / "t.jsonl.data"
    gz.write_bytes(gzip.compress(plain.read_bytes()))
    assert list(parse_borg(str(plain))) == list(parse_borg(str(gz)))


def test_truncated_gz_raises_trace_error(tmp_path):
    payload = gzip.compress(
        ("\n".join(_borg_line(i * 1_000_000, 0, 1, i, prio=0) for i in range(200))).encode()
    )
    trunc = tmp_path / "trunc.gz"
    trunc.write_bytes(payload[: len(payload) // 2])
    with pytest.raises(TraceError, match="corrupt trace"):
        list(parse_borg(str(trunc)))


def test_byte_bound_refuses_oversized_input(tmp_path):
    big = tmp_path / "big.jsonl"
    big.write_text("x" * 1024)
    with pytest.raises(TraceError, match="exceeds the 100-byte bound"):
        list(open_trace_lines(str(big), max_bytes=100))


def test_byte_bound_from_environment(tmp_path, monkeypatch):
    big = tmp_path / "big.jsonl"
    big.write_text("y" * 2048)
    monkeypatch.setenv("KSIM_TRACES_MAX_BYTES", "64")
    with pytest.raises(TraceError, match="64-byte bound"):
        list(open_trace_lines(str(big)))


def test_missing_file_raises_trace_error():
    with pytest.raises(TraceError, match="cannot read trace"):
        list(parse_borg("/nonexistent/trace.jsonl"))


# ---------------------------------------------------------------------------
# Resample: determinism + distribution preservation
# ---------------------------------------------------------------------------


def _mk_records(n: int) -> list[TraceRecord]:
    return [
        TraceRecord(
            name=f"t{i}",
            arrival_s=float(i),
            cpu_milli=100 * (1 + i % 4),
            mem_mib=128,
            lifetime_s=10.0 if i % 2 else 0.0,
            tier=i % 5,
            priority=i,
        )
        for i in range(n)
    ]


def test_resample_sorts_out_of_order_arrivals():
    recs = _mk_records(10)[::-1]  # reversed arrival order (Borg yields at close)
    out = resample(recs)
    assert [r.arrival_s for r in out] == sorted(r.arrival_s for r in out)


def test_resample_is_seed_deterministic():
    recs = _mk_records(200)
    a = resample(recs, seed=7, max_events=50)
    b = resample(recs, seed=7, max_events=50)
    c = resample(recs, seed=8, max_events=50)
    assert a == b
    assert a != c  # a different seed picks a different subset
    assert sum(2 if r.lifetime_s > 0 else 1 for r in a) <= 50


def test_resample_no_budget_keeps_everything():
    recs = _mk_records(20)
    assert len(resample(recs)) == 20
    assert len(resample(recs, max_events=10_000)) == 20


def test_resample_node_rescale_thins_proportionally():
    recs = _mk_records(2000)
    out = resample(recs, seed=0, target_nodes=100, source_nodes=1000)
    # ~10% survive (uniform, independent draws): wide deterministic band.
    assert 120 <= len(out) <= 280
    # Uniform thinning preserves the tier mix (each tier is 20% +- noise).
    from collections import Counter

    tiers = Counter(r.tier for r in out)
    for t in range(5):
        assert tiers[t] / len(out) == pytest.approx(0.2, abs=0.08)


def test_resample_rejects_bad_node_counts():
    with pytest.raises(TraceError):
        resample(_mk_records(4), target_nodes=0, source_nodes=10)


# ---------------------------------------------------------------------------
# Compile: vocabulary, grid, priority ladder
# ---------------------------------------------------------------------------


def test_compile_emits_only_replay_vocabulary():
    ops = compile_trace(_mk_records(30), n_nodes=4, ops_per_step=5)
    assert all(op.kind in ("nodes", "pods") for op in ops)
    assert all(op.op in ("create", "delete") for op in ops)
    nodes = [op for op in ops if op.kind == "nodes"]
    assert len(nodes) == 4 and all(op.step == 0 for op in nodes)
    # Steps are sorted and pod names unique.
    assert [op.step for op in ops] == sorted(op.step for op in ops)
    names = [op.obj["metadata"]["name"] for op in ops if op.kind == "pods" and op.op == "create"]
    assert len(set(names)) == len(names)


def test_compile_deletes_follow_creates_with_exact_quantities():
    recs = [
        TraceRecord(name="A_1", arrival_s=0.0, cpu_milli=750, mem_mib=300,
                    lifetime_s=5.0, tier=2, priority=117),
        TraceRecord(name="b", arrival_s=9.0, cpu_milli=100, mem_mib=64,
                    lifetime_s=0.0, tier=0, priority=0),
    ]
    ops = compile_trace(recs, n_nodes=2, ops_per_step=1)
    pods = [op for op in ops if op.kind == "pods"]
    creates = [op for op in pods if op.op == "create"]
    deletes = [op for op in pods if op.op == "delete"]
    assert len(creates) == 2 and len(deletes) == 1  # b has no known lifetime
    by_name = {op.obj["metadata"]["name"]: op for op in creates}
    (a_name,) = [n for n in by_name if "a-1" in n]  # sanitized to k8s charset
    a = by_name[a_name]
    req = a.obj["spec"]["containers"][0]["resources"]["requests"]
    assert req == {"cpu": "750m", "memory": "300Mi"}
    assert a.obj["spec"]["priority"] == PRIORITY_LADDER[2]
    (d,) = deletes
    assert d.name == a_name and d.step >= a.step


def test_compile_priority_ladder_per_tier():
    recs = [
        TraceRecord(name=f"t{t}", arrival_s=float(t), cpu_milli=100, mem_mib=64, tier=t)
        for t in range(5)
    ]
    ops = compile_trace(recs, n_nodes=1, ops_per_step=1)
    prios = [
        op.obj["spec"]["priority"]
        for op in ops
        if op.kind == "pods" and op.op == "create"
    ]
    assert prios == list(PRIORITY_LADDER)


def test_compile_grid_preserves_burstiness():
    """A fixed tick, not a fixed batch: an arrival burst lands in few
    steps, a quiet stretch spreads thin."""
    recs = [
        TraceRecord(name=f"q{i}", arrival_s=float(i * 10), cpu_milli=10, mem_mib=16)
        for i in range(10)
    ] + [
        TraceRecord(name=f"b{i}", arrival_s=95.0, cpu_milli=10, mem_mib=16)
        for i in range(10)
    ]
    ops = compile_trace(recs, n_nodes=1, ops_per_step=2)
    from collections import Counter

    per_step = Counter(op.step for op in ops if op.kind == "pods")
    assert max(per_step.values()) >= 10  # the burst stayed a burst


def test_compile_refusals():
    with pytest.raises(TraceError, match="zero records"):
        compile_trace([], n_nodes=4)
    with pytest.raises(TraceError, match="n_nodes"):
        compile_trace(_mk_records(3), n_nodes=0)
    with pytest.raises(TraceError, match="ops_per_step"):
        compile_trace(_mk_records(3), n_nodes=2, ops_per_step=0)


# ---------------------------------------------------------------------------
# Registry: allowlisted names only
# ---------------------------------------------------------------------------


def test_registry_resolves_names_in_traces_dir(tmp_path, monkeypatch):
    (tmp_path / "mini.jsonl").write_text("")
    (tmp_path / ".hidden").write_text("")
    monkeypatch.setenv("KSIM_TRACES_DIR", str(tmp_path))
    assert list_traces() == ["mini.jsonl"]
    assert resolve("mini.jsonl") == str(tmp_path / "mini.jsonl")


def test_registry_refuses_traversal_and_unknown(tmp_path, monkeypatch):
    monkeypatch.setenv("KSIM_TRACES_DIR", str(tmp_path))
    for bad in ("../etc/passwd", "a/b.jsonl", ".hidden", ""):
        with pytest.raises(TraceError):
            resolve(bad)
    with pytest.raises(TraceError, match="no registered trace"):
        resolve("missing.jsonl")


def test_registry_unconfigured_refuses(monkeypatch):
    monkeypatch.delenv("KSIM_TRACES_DIR", raising=False)
    assert list_traces() == []
    with pytest.raises(TraceError, match="no trace registry configured"):
        resolve("anything.jsonl")


# ---------------------------------------------------------------------------
# Bundled fixtures stay parseable (the replay lock lives in
# tests/test_behavior_locks.py)
# ---------------------------------------------------------------------------


def test_bundled_borg_fixture_parses():
    recs = list(parse_borg(f"{FIXTURES}/borg_mini.jsonl"))
    assert len(recs) == 61  # 60 instances + 1 resubmit lifetime
    assert {r.tier for r in recs} == {0, 1, 2, 3, 4}
    names = [r.name for r in recs]
    assert len(set(names)) == len(names)


def test_bundled_alibaba_fixture_parses():
    recs = list(parse_alibaba(f"{FIXTURES}/alibaba_batch_mini.csv"))
    assert len(recs) == 24
    assert all(r.kind == "batch" and r.tier == 1 for r in recs)
    assert sum(1 for r in recs if r.lifetime_s > 0) == 22  # 2 Running rows


def test_borg_malformed_priority_and_request_raise_parse_errors():
    """Malformed priority/resource_request fields stay inside the
    strict-with-line-number contract (a bare ValueError would escape
    the TraceError -> HTTP 400 mapping at the spec/job surface)."""
    with pytest.raises(TraceParseError, match="line 1: non-numeric priority"):
        list(parse_borg([
            '{"time": 0, "type": 0, "collection_id": 1, "instance_index": 0, "priority": "high"}'
        ]))
    with pytest.raises(TraceParseError, match="line 1: resource_request must be an object"):
        list(parse_borg([
            '{"time": 0, "type": 0, "collection_id": 1, "instance_index": 0, "resource_request": "0.5"}'
        ]))
    with pytest.raises(TraceParseError, match="line 1: non-numeric"):
        list(parse_borg([
            '{"time": 0, "type": 0, "collection_id": 1, "instance_index": 0, "resource_request": {"cpus": "lots"}}'
        ]))
