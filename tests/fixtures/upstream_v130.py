"""Hand-derived parity fixtures from upstream kube-scheduler v1.30 formulas.

These expected values were computed BY HAND from the upstream plugin
algorithms (files cited per block), with every arithmetic step documented
— NOT by running the repo's oracle or kernels.  They exist to break the
oracle-validates-kernel circularity: tests/test_upstream_fixtures.py
asserts that the pure-Python oracle AND the JAX kernels both reproduce
these independently-derived numbers.  If either implementation
mis-derives an upstream formula, it now disagrees with a number computed
straight from the formula's definition rather than with its twin.

Sources are unavailable to vendoring in this environment, so scenarios
are original (not copies of upstream test tables), but each follows the
canonical shapes those tables exercise.  Float-sensitive expectations
were evaluated with IEEE-754 float64 arithmetic (identical in Go and
Python); integer expectations use the upstream integer division order.
"""

from __future__ import annotations

MB = 1024 * 1024
GI = 1024 * 1024 * 1024

# Upstream nonzero.go: GetNonzeroRequests defaults when a pod declares no
# request for the resource (DefaultMilliCPURequest / DefaultMemoryRequest).
NONZERO_CPU_MILLI = 100
NONZERO_MEMORY = 200 * MB

# ---------------------------------------------------------------------------
# NodeResourcesBalancedAllocation
# (pkg/scheduler/framework/plugins/noderesources/balanced_allocation.go,
#  balancedResourceScorer): fraction_r = requested_r / allocatable_r
# (clamped to 1), std = |f_cpu - f_mem| / 2 for two resources, score =
# int64((1 - std) * 100) — float64 arithmetic throughout.
#
# Node quantities: cpu in milli, memory in bytes.  Pods declare explicit
# requests unless noted (the no-request case exercises the nonzero
# defaults above).
# ---------------------------------------------------------------------------

BALANCED_ALLOCATION_CASES = [
    {
        # f_cpu = 3000/4000 = 0.75, f_mem = 5000/10000 = 0.5
        # std = |0.75 - 0.5| / 2 = 0.125 -> int((1 - 0.125) * 100) = 87
        "name": "skewed-cpu",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "want": 87,
    },
    {
        # f_cpu = 3000/6000 = 0.5, f_mem = 0.5 -> std 0 -> 100
        "name": "perfectly-balanced",
        "node_cpu_milli": 6000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "want": 100,
    },
    {
        # f_cpu = 0.5, f_mem = 0.4 -> std = 0.05
        # float64: (1 - 0.05) * 100 = 95.00000000000001 -> 95
        "name": "small-skew-float64-rounding",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 2000,
        "pod_mem": 4000,
        "want": 95,
    },
    {
        # No requests -> nonzero defaults 100m / 200Mi on a 1-CPU / 1-Gi
        # node: f_cpu = 100/1000 = 0.1, f_mem = 200Mi/1Gi = 0.1953125
        # std = 0.04765625 -> int(95.234375) = 95
        "name": "nonzero-defaults",
        "node_cpu_milli": 1000,
        "node_mem": GI,
        "pod_cpu_milli": None,
        "pod_mem": None,
        "want": 95,
    },
]

# ---------------------------------------------------------------------------
# NodeResourcesFit score = LeastAllocated
# (noderesources/resource_allocation.go + least_allocated.go,
#  leastResourceScorer): per resource (weight 1 each for cpu/memory):
#    score_r = ((allocatable - requested) * 100) / allocatable   [int64 div]
#    0 when requested > allocatable
#  node score = sum(score_r * w_r) / sum(w_r)                    [int64 div]
# ---------------------------------------------------------------------------

LEAST_ALLOCATED_CASES = [
    {
        # cpu (4000-1000)*100/4000 = 75; mem (10000-2000)*100/10000 = 80
        # (75 + 80) / 2 = 77  [integer division]
        "name": "light-load",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 1000,
        "pod_mem": 2000,
        "want": 77,
    },
    {
        # cpu (1000*100)/4000 = 25; mem (5000*100)/10000 = 50 -> 75/2 = 37
        "name": "heavy-load",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "want": 37,
    },
    {
        # cpu requested 5000 > allocatable 4000 -> 0; mem 50 -> 50/2 = 25
        "name": "over-requested-cpu-scores-zero",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 5000,
        "pod_mem": 5000,
        "want": 25,
    },
    {
        # nonzero defaults on 1 CPU / 1 Gi:
        # cpu (1000-100)*100/1000 = 90
        # mem (1073741824-209715200)*100/1073741824 = 86402662400/1073741824
        #     = 80  [floor of 80.468...]
        # (90 + 80) / 2 = 85
        "name": "nonzero-defaults",
        "node_cpu_milli": 1000,
        "node_mem": GI,
        "pod_cpu_milli": None,
        "pod_mem": None,
        "want": 85,
    },
]

# ---------------------------------------------------------------------------
# TaintToleration score
# (tainttoleration/taint_toleration.go): raw score = count of the node's
# PreferNoSchedule taints the pod does NOT tolerate; NormalizeScore =
# helper.DefaultNormalizeScore(100, reverse=true):
#    max = max(raw); normalized_i = 100 - (100 * raw_i / max)  [int64 div]
#    (all 100 when max == 0)
# ---------------------------------------------------------------------------

# Node i carries i PreferNoSchedule taints, pod tolerates none:
# raw = [0, 1, 2]; max = 2
# normalized = [100 - 0, 100 - 100*1/2, 100 - 100*2/2] = [100, 50, 0]
TAINT_PREFER_COUNTS = [0, 1, 2]
TAINT_EXPECT_RAW = [0, 1, 2]
TAINT_EXPECT_NORMALIZED = [100, 50, 0]

# ---------------------------------------------------------------------------
# ImageLocality
# (imagelocality/image_locality.go): for each container whose image the
# node holds: scaledImageScore = int64(size * (numNodesWithImage /
# totalNumNodes)); sumScores over containers; then calculatePriority:
#    minThreshold = 23 MB, maxThreshold = 1000 MB * numContainers
#    clamped = clamp(sumScores, minThreshold, maxThreshold)
#    score = int64(100 * (clamped - minThreshold) / (maxThreshold - minThreshold))
# ---------------------------------------------------------------------------

IMAGE_LOCALITY_CASES = [
    {
        # 2 nodes; only node-a holds img-big (300 MB, numNodes=1):
        # scaled = int(300MB * 1/2) = 150 MB
        # node-a: 100 * (150-23)MB / (1000-23)MB = 12700/977 = 12.99 -> 12
        # node-b: sum 0 -> clamps to minThreshold -> 0
        "name": "single-container-half-spread",
        "images": {"img-big": {"size": 300 * MB, "on": ["node-a"]}},
        "pod_images": ["img-big"],
        "want": {"node-a": 12, "node-b": 0},
    },
    {
        # img-everywhere 500 MB on both nodes (numNodes=2, scaled 500 MB),
        # img-rare 200 MB only on node-a (scaled 100 MB); 2 containers:
        # node-a: sum = 600 MB; maxThreshold = 2000 MB
        #   100 * (600-23) / (2000-23) = 57700/1977 = 29.18 -> 29
        # node-b: sum = 500 MB -> 100 * 477/1977 = 24.12 -> 24
        "name": "two-containers-mixed-spread",
        "images": {
            "img-everywhere": {"size": 500 * MB, "on": ["node-a", "node-b"]},
            "img-rare": {"size": 200 * MB, "on": ["node-a"]},
        },
        "pod_images": ["img-everywhere", "img-rare"],
        "want": {"node-a": 29, "node-b": 24},
    },
]

# ---------------------------------------------------------------------------
# PodTopologySpread filter (podtopologyspread/filtering.go):
# For each DoNotSchedule constraint: matchNum(domain) = count of existing
# pods matching the labelSelector in that topology domain;
# minMatch = min over all domains present among eligible nodes;
# candidate node violates iff matchNum(node's domain) + 1 - minMatch > maxSkew.
# Nodes missing the topology key always fail that constraint.
#
# The incoming pod is itself labeled foo=bar, so selfMatchNum = 1
# (upstream filtering.go: skew = matchNum + selfMatchNum - minMatchNum).
#
# Topology: zone1 = {node-a, node-b}, zone2 = {node-x, node-y}; every node
# also has its own hostname label.  Existing pods labeled foo=bar: 2 on
# node-a, 0 elsewhere.
#
# zone-only constraint (maxSkew=1): domains zone1=2, zone2=0, min=0
#   node-a/node-b: 2+1-0 = 3 > 1 -> violate; node-x/node-y: 0+1-0 = 1 -> ok
# hostname-only constraint (maxSkew=1): domains a=2 b=0 x=0 y=0, min=0
#   node-a: 2+1-0 = 3 > 1 -> violate; b/x/y: 0+1-0 = 1 -> ok
#   (node-b passes here but fails the zone constraint — the two
#   constraints are distinguishable.)
# ---------------------------------------------------------------------------

SPREAD_EXISTING = {"node-a": 2, "node-b": 0, "node-x": 0, "node-y": 0}
SPREAD_ZONE_ONLY_EXPECT = {  # True = violates
    "node-a": True,
    "node-b": True,
    "node-x": False,
    "node-y": False,
}
SPREAD_HOSTNAME_ONLY_EXPECT = {
    "node-a": True,
    "node-b": False,
    "node-x": False,
    "node-y": False,
}
SPREAD_BOTH_EXPECT = {
    "node-a": True,
    "node-b": True,
    "node-x": False,
    "node-y": False,
}

# ScheduleAnyway scoring is ordinal here (the v1.30 scoring internals
# carry normalizing weights; the ordering over domains is the contract):
# fewer matching pods in the candidate's domain => strictly higher score.
# hostname counts a=2, b=1, x=y=0  ->  score(x) == score(y) > score(b) > score(a)
SPREAD_SCORE_EXISTING = {"node-a": 2, "node-b": 1, "node-x": 0, "node-y": 0}

# ---------------------------------------------------------------------------
# InterPodAffinity (interpodaffinity/filtering.go + scoring.go):
# required podAffinity: candidate node's topology domain must already hold
#   a pod matching the term (or the incoming pod may match its own term's
#   selector+namespace when the domain holds no pod at all — the
#   first-pod-of-series escape).
# required podAntiAffinity: candidate's domain must hold NO matching pod;
#   symmetric: an existing pod's required anti-affinity term matching the
#   incoming pod blocks that existing pod's domain.
# preferred scoring: for each existing pod and each weighted term of the
#   incoming pod that matches it, every node in the existing pod's domain
#   gains the weight; NormalizeScore scales linearly so max -> 100, min -> 0:
#     normalized_i = int(100 * (raw_i - min) / (max - min))   [float64]
# ---------------------------------------------------------------------------

# Topology again zone1={node-a,node-b}, zone2={node-x,node-y}.
# Existing: app=db pod on node-a.
# Incoming requires podAffinity to app=db over "zone":
IPA_REQUIRED_AFFINITY_EXPECT = {
    "node-a": True,  # zone1 holds the db pod
    "node-b": True,
    "node-x": False,
    "node-y": False,
}
# Existing: app=web pod on node-x.  Incoming requires podAntiAffinity to
# app=web over "zone":
IPA_REQUIRED_ANTI_EXPECT = {
    "node-a": True,
    "node-b": True,
    "node-x": False,
    "node-y": False,
}
# Existing pod on node-b carries required anti-affinity to team=t1 over
# "hostname"; incoming pod is labeled team=t1: only node-b is blocked.
IPA_EXISTING_ANTI_EXPECT = {
    "node-a": True,
    "node-b": False,
    "node-x": True,
    "node-y": True,
}
# Preferred affinity weight 5 to app=db over "zone", db pod on node-a:
# raw = [5, 5, 0, 0] -> min 0, max 5 -> normalized [100, 100, 0, 0]
IPA_PREFERRED_WEIGHT = 5
IPA_PREFERRED_EXPECT_NORMALIZED = {
    "node-a": 100,
    "node-b": 100,
    "node-x": 0,
    "node-y": 0,
}


# ---------------------------------------------------------------------------
# NodeResourcesFit scoring strategy: MostAllocated
# (pkg/scheduler/framework/plugins/noderesources/most_allocated.go,
#  mostResourceScorer): per-resource min(requested, allocatable) * 100 //
# allocatable (integer division), weight-averaged with integer division;
# zero-allocatable resources are skipped.  Requested uses the non-zero
# accumulation (nonzero.go defaults when the pod declares no request).
# ---------------------------------------------------------------------------

MOST_ALLOCATED_CASES = [
    {
        # cpu: 3000 * 100 // 4000 = 75;  mem: 5000 * 100 // 10000 = 50
        # (75*1 + 50*1) // 2 = 62
        "name": "plain",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 62,
    },
    {
        # cpu overcommit clamps: min(3000, 2000) = 2000 -> 2000*100//2000
        # = 100;  mem: 50 -> (100 + 50) // 2 = 75
        "name": "overcommit-clamps",
        "node_cpu_milli": 2000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 75,
    },
    {
        # weighted: (75*3 + 50*1) // (3+1) = 275 // 4 = 68
        "name": "weighted",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 3), ("memory", 1)),
        "want": 68,
    },
    {
        # no requests -> nonzero defaults 100m / 200Mi:
        # cpu: 100 * 100 // 1000 = 10
        # mem: (200Mi * 100) // 1000Mi = 20   (Mi factors cancel)
        # (10 + 20) // 2 = 15
        "name": "nonzero-defaults",
        "node_cpu_milli": 1000,
        "node_mem": 1000 * MB,
        "pod_cpu_milli": None,
        "pod_mem": None,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 15,
    },
]

# ---------------------------------------------------------------------------
# NodeResourcesFit scoring strategy: RequestedToCapacityRatio
# (noderesources/requested_to_capacity_ratio.go +
#  helper/shape_score.go BuildBrokenLinearFunction):
#   - shape scores are config 0..10, scaled x10 to MaxNodeScore range;
#   - utilization p = requested * 100 // allocatable (Go integer division);
#     zero allocatable or requested > allocatable evaluate the shape at
#     p = 100;
#   - broken-linear: first i with p <= u_i interpolates
#     s_{i-1} + (s_i - s_{i-1}) * (p - u_{i-1}) / (u_i - u_{i-1})
#     with Go division (truncates toward ZERO — differs from floor when
#     the slope is negative);
#   - only resources with score > 0 enter the weight sum (upstream quirk);
#   - final: math.Round(nodeScore / weightSum), half away from zero.
# ---------------------------------------------------------------------------

RTCR_CASES = [
    {
        # shape 0->0, 100->10 (most-requested ramp), scaled (0,0),(100,100).
        # cpu p = 3000*100//4000 = 75 -> 0 + (100-0)*(75-0)/100 = 75
        # mem p = 5000*100//10000 = 50 -> 50
        # round((75 + 50) / 2) = round(62.5) = 63  [differs from
        # MostAllocated's 62: Round vs integer division]
        "name": "ramp-up",
        "shape": ((0, 0), (100, 10)),
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 63,
    },
    {
        # shape 0->10, 100->0 (least-requested ramp), scaled (0,100),(100,0).
        # cpu p = 75 -> 100 + (0-100)*75/100 = 100 + trunc(-75.0) = 25
        # mem p = 50 -> 50
        # round((25 + 50) / 2) = round(37.5) = 38
        "name": "ramp-down",
        "shape": ((0, 10), (100, 0)),
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 38,
    },
    {
        # Truncation direction: shape (0,10),(3,0) scaled (0,100),(3,0).
        # cpu: pod 10m of 1000m -> p = 10*100//1000 = 1
        #   -> 100 + (0-100)*(1-0)/3 = 100 + trunc(-100/3) = 100 - 33 = 67
        #   (floor division would give 100 - 34 = 66)
        # mem: default 200Mi of 200Gi -> p = 200Mi*100 // 200Gi
        #   = 100 // 1024 = 0 -> p <= u_0 = 0 -> s_0 = 100
        # round((67 + 100) / 2) = round(83.5) = 84
        "name": "trunc-toward-zero",
        "shape": ((0, 10), (3, 0)),
        "node_cpu_milli": 1000,
        "node_mem": 200 * GI,
        "pod_cpu_milli": 10,
        "pod_mem": None,  # pod declares no memory request
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 84,
    },
    {
        # Zero scores leave the weight sum: shape (0,0),(100,10).
        # cpu: default 100m of 20000m -> p = 100*100//20000 = 0 -> score 0
        #   -> EXCLUDED from weightSum
        # mem: default 200Mi of 400Mi -> p = 200*100//400 = 50 -> score 50
        # weightSum = 1 -> round(50 / 1) = 50
        # (a naive implementation averaging over both weights gives 25)
        "name": "zero-score-excluded",
        "shape": ((0, 0), (100, 10)),
        "node_cpu_milli": 20000,
        "node_mem": 400 * MB,
        "pod_cpu_milli": None,
        "pod_mem": None,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 50,
    },
    {
        # Weighted rounding: cpu w2 p75 -> 75*2 = 150; mem w1 p50 -> 50.
        # round((150 + 50) / 3) = round(66.67) = 67
        "name": "weighted-round",
        "shape": ((0, 0), (100, 10)),
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 2), ("memory", 1)),
        "want": 67,
    },
    {
        # Three-point shape exercises the MIDDLE segment: (0,0),(50,10),
        # (100,5) scaled (0,0),(50,100),(100,50).
        # cpu p = 75 -> segment (50,100]: 100 + (50-100)*(75-50)/(100-50)
        #   = 100 + trunc(-1250/50) = 100 - 25 = 75
        # mem p = 50 -> first i with p <= u_i is i=1:
        #   0 + (100-0)*(50-0)/(50-0) = 100
        # round((75 + 100) / 2) = round(87.5) = 88
        "name": "three-point-middle-segment",
        "shape": ((0, 0), (50, 10), (100, 5)),
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 1), ("memory", 1)),
        "want": 88,
    },
]

# ---------------------------------------------------------------------------
# NodeAffinityArgs.addedAffinity
# (plugins/nodeaffinity/node_affinity.go: New parses
#  args.AddedAffinity; Filter checks the added required selector FIRST and
#  early-returns "node(s) didn't match scheduler-enforced node affinity";
#  Score adds the added preferred terms' weights for every pod, then
#  DefaultNormalizeScore.)
#
# Nodes: n-a labels {zone: a, hw: x}; n-b labels {zone: b, hw: x}.
# addedAffinity required: zone In [a]; addedAffinity preferred:
# weight 10 -> zone In [a].
# ---------------------------------------------------------------------------

ADDED_AFFINITY_REQUIRED = {
    "requiredDuringSchedulingIgnoredDuringExecution": {
        "nodeSelectorTerms": [
            {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]}
        ]
    }
}
ADDED_AFFINITY_PREFERRED = {
    "preferredDuringSchedulingIgnoredDuringExecution": [
        {
            "weight": 10,
            "preference": {
                "matchExpressions": [
                    {"key": "zone", "operator": "In", "values": ["a"]}
                ]
            },
        }
    ]
}
# Plain pod under the required addedAffinity: n-a passes, n-b fails with
# the enforced reason only.
ADDED_AFFINITY_FILTER_EXPECT = {"n-a": [], "n-b": ["node(s) didn't match scheduler-enforced node affinity"]}
# Pod whose own nodeSelector wants zone=b: n-a fails the POD reason
# (added check passed), n-b fails the ENFORCED reason (early return).
ADDED_AFFINITY_CROSS_EXPECT = {
    "n-a": ["node(s) didn't match Pod's node affinity/selector"],
    "n-b": ["node(s) didn't match scheduler-enforced node affinity"],
}
# Score under ADDED_AFFINITY_PREFERRED plus a pod preferred term
# weight 5 -> hw In [x] (matches both nodes):
#   raw n-a = 10 + 5 = 15, raw n-b = 5
#   DefaultNormalizeScore(reverse=False): max = 15
#     n-a = 100 * 15 // 15 = 100;  n-b = 100 * 5 // 15 = 33
ADDED_AFFINITY_SCORE_EXPECT = {"n-a": 100, "n-b": 33}

# ---------------------------------------------------------------------------
# Legacy non-CSI volume-limit plugins: EBSLimits / GCEPDLimits /
# AzureDiskLimits / CinderLimits (nodevolumelimits/non_csi.go; the
# reference's exported default config enables the first three in its
# filter list, simulator/snapshot/snapshot_test.go:1415).  Each counts
# DISTINCT volumes of its one type against the node's
# attachable-volumes-<pool> allocatable; failure reason is
# "node(s) exceed max volume count".
#
# Scenario: node exposes attachable-volumes-aws-ebs = 1 and already runs
# a bound pod attached to EBS volume vol-1.
#   - queue pod with EBS vol-2: 1 attached + 1 new = 2 > 1 -> rejected
#   - queue pod re-using vol-1: dedup -> 1 attached + 0 new -> fits
#   - GCEPDLimits checks only the gce-pd pool -> the vol-2 pod passes it
# ---------------------------------------------------------------------------

EBS_LIMIT_REASON = "node(s) exceed max volume count"


# ---------------------------------------------------------------------------
# PodTopologySpread per-constraint policies (v1.30 common.go/filtering.go):
#
# - nodeAffinityPolicy (default Honor): Honor excludes nodes failing the
#   POD's nodeSelector/required-affinity from domain counting; Ignore
#   counts them.
# - nodeTaintsPolicy (default Ignore): Honor excludes nodes whose taints
#   the incoming pod does not tolerate.
# - matchLabelKeys (beta, on): each key folds the incoming pod's own
#   label value into the selector as an In-requirement.
# - Filter skew for node n: matchNum(n's domain; 0 when the domain was
#   excluded) + selfMatch(1 if the pod matches its own selector)
#   - minMatchNum(over ELIGIBLE domains); violates when > maxSkew.
#
# Scenario T (taints policy): zone A node a1 (untainted) runs 2 app=web
# pods; zone B node b1 carries an intolerable NoSchedule taint and runs
# none.  Incoming app=web, maxSkew 1, DoNotSchedule over zone.
#   Ignore (default): min over {A:2, B:0} = 0 -> a1 skew 2+1-0=3 >1
#     VIOLATES; b1 skew 0+1-0=1 passes.
#   Honor: B excluded -> min over {A}=2 -> a1 skew 3-2=1 passes;
#     b1 matchNum 0 -> skew 1-2=-1 passes.
SPREAD_TAINTS_POLICY_EXPECT = {
    "Ignore": {"a1": True, "b1": False},   # True = spread VIOLATION
    "Honor": {"a1": False, "b1": False},
}

# Scenario N (affinity policy): a1 {zone A, tier frontend} runs 2
# app=web pods; b1 {zone B} lacks tier.  Incoming has nodeSelector
# tier=frontend, same constraint.
#   Honor (default): b1 excluded -> min=2 -> a1 skew 1 passes.
#   Ignore: min=0 -> a1 skew 3 VIOLATES; b1 passes (its own NodeAffinity
#     failure is a different plugin's verdict).
SPREAD_AFFINITY_POLICY_EXPECT = {
    "Honor": {"a1": False, "b1": False},
    "Ignore": {"a1": True, "b1": False},
}

# Scenario M (matchLabelKeys): a1 zone A runs 2 {app web, version v1}
# pods; b1 zone B runs 1 {app web, version v2}.  Incoming {app web,
# version v2}, selector app=web, maxSkew 1, DoNotSchedule over zone.
#   With matchLabelKeys [version]: effective selector app=web AND
#     version=v2 -> counts A=0, B=1, min=0 -> a1 skew 0+1-0=1 passes;
#     b1 skew 1+1-0=2 VIOLATES.
#   Without: counts A=2, B=1, min=1 -> a1 skew 2+1-1=2 VIOLATES;
#     b1 skew 1+1-1=1 passes.  (Full inversion.)
SPREAD_MATCH_LABEL_KEYS_EXPECT = {
    "with": {"a1": False, "b1": True},
    "without": {"a1": True, "b1": False},
}


# ---------------------------------------------------------------------------
# NodeResourcesFit scoring strategy: LeastAllocated with CUSTOM weights
# (resource_allocation.go score): per resource with allocatable > 0,
#   nodeScore += leastRequestedScore * weight; weightSum += weight;
# resources the NODE lacks are skipped entirely (alloc == 0 -> continue,
# weight NOT counted); final = nodeScore // weightSum (int64 division).
# Hand-derived, never from the oracle.
# ---------------------------------------------------------------------------

LEAST_ALLOCATED_WEIGHTED_CASES = [
    {
        # cpu (4000-3000)*100//4000 = 25; mem (10000-5000)*100//10000 = 50
        # weights cpu=3, mem=1: (25*3 + 50*1) // 4 = 125 // 4 = 31
        "name": "weighted-3-1",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "weights": (("cpu", 3), ("memory", 1)),
        "want": 31,
    },
    {
        # The node has NO example.com/gpu allocatable: that resource is
        # skipped and its weight 5 never enters the weight sum.
        # cpu (4000-1000)*100//4000 = 75; mem (10000-2000)*100//10000 = 80
        # (75*1 + 80*1) // 2 = 77   (NOT (75+80)//7 = 22)
        "name": "missing-resource-weight-excluded",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 1000,
        "pod_mem": 2000,
        "weights": (("cpu", 1), ("memory", 1), ("example.com/gpu", 5)),
        "want": 77,
    },
]

# ---------------------------------------------------------------------------
# TaintToleration: NoExecute taints filter at SCHEDULING time too
# (taint_toleration.go Filter uses FindMatchingUntoleratedTaint over
# NoSchedule AND NoExecute; tolerationSeconds only matters to eviction,
# never to the scheduling-time match — tolerations.go
# TolerationsTolerateTaint ignores it).
# ---------------------------------------------------------------------------

NO_EXECUTE_TAINT = {"key": "maint", "value": "now", "effect": "NoExecute"}
# Exact upstream reason (taint_toleration.go errReasonNotMatch format).
NO_EXECUTE_REASON = "node(s) had untolerated taint {maint: now}"
# A toleration whose tolerationSeconds would evict after 300s still
# ADMITS the pod at scheduling time.
NO_EXECUTE_TOLERATION = {
    "key": "maint",
    "operator": "Equal",
    "value": "now",
    "effect": "NoExecute",
    "tolerationSeconds": 300,
}


# ---------------------------------------------------------------------------
# BalancedAllocation over THREE configured resources
# (balanced_allocation.go balancedResourceScorer with
#  NodeResourcesBalancedAllocationArgs.resources adding an extended
#  resource): fractions f_r = requested/allocatable; mean over the
#  configured set; std = sqrt(sum((f - mean)^2) / len); score =
#  int((1 - std) * 100) in float64.
#
# Hand-derived (all fractions exact in binary):
#   f_cpu = 3000/4000 = 0.75, f_mem = 5000/10000 = 0.5, f_gpu = 1/4 = 0.25
#   mean = 0.5; deviations (0.25, 0, -0.25); sum sq = 0.125
#   std = sqrt(0.125/3) = sqrt(0.04166666666666666...)
#       = 0.20412414523193148 (float64)
#   (1 - std) * 100 = 79.58758547680685 -> int -> 79
# ---------------------------------------------------------------------------

BALANCED_THREE_RESOURCE_CASE = {
    "node_cpu_milli": 4000,
    "node_mem": 10000,
    "node_gpu": 4,
    "pod_cpu_milli": 3000,
    "pod_mem": 5000,
    "pod_gpu": 1,
    "resources": ("cpu", "memory", "example.com/gpu"),
    "want": 79,
}
