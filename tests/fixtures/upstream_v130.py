"""Hand-derived parity fixtures from upstream kube-scheduler v1.30 formulas.

These expected values were computed BY HAND from the upstream plugin
algorithms (files cited per block), with every arithmetic step documented
— NOT by running the repo's oracle or kernels.  They exist to break the
oracle-validates-kernel circularity: tests/test_upstream_fixtures.py
asserts that the pure-Python oracle AND the JAX kernels both reproduce
these independently-derived numbers.  If either implementation
mis-derives an upstream formula, it now disagrees with a number computed
straight from the formula's definition rather than with its twin.

Sources are unavailable to vendoring in this environment, so scenarios
are original (not copies of upstream test tables), but each follows the
canonical shapes those tables exercise.  Float-sensitive expectations
were evaluated with IEEE-754 float64 arithmetic (identical in Go and
Python); integer expectations use the upstream integer division order.
"""

from __future__ import annotations

MB = 1024 * 1024
GI = 1024 * 1024 * 1024

# Upstream nonzero.go: GetNonzeroRequests defaults when a pod declares no
# request for the resource (DefaultMilliCPURequest / DefaultMemoryRequest).
NONZERO_CPU_MILLI = 100
NONZERO_MEMORY = 200 * MB

# ---------------------------------------------------------------------------
# NodeResourcesBalancedAllocation
# (pkg/scheduler/framework/plugins/noderesources/balanced_allocation.go,
#  balancedResourceScorer): fraction_r = requested_r / allocatable_r
# (clamped to 1), std = |f_cpu - f_mem| / 2 for two resources, score =
# int64((1 - std) * 100) — float64 arithmetic throughout.
#
# Node quantities: cpu in milli, memory in bytes.  Pods declare explicit
# requests unless noted (the no-request case exercises the nonzero
# defaults above).
# ---------------------------------------------------------------------------

BALANCED_ALLOCATION_CASES = [
    {
        # f_cpu = 3000/4000 = 0.75, f_mem = 5000/10000 = 0.5
        # std = |0.75 - 0.5| / 2 = 0.125 -> int((1 - 0.125) * 100) = 87
        "name": "skewed-cpu",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "want": 87,
    },
    {
        # f_cpu = 3000/6000 = 0.5, f_mem = 0.5 -> std 0 -> 100
        "name": "perfectly-balanced",
        "node_cpu_milli": 6000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "want": 100,
    },
    {
        # f_cpu = 0.5, f_mem = 0.4 -> std = 0.05
        # float64: (1 - 0.05) * 100 = 95.00000000000001 -> 95
        "name": "small-skew-float64-rounding",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 2000,
        "pod_mem": 4000,
        "want": 95,
    },
    {
        # No requests -> nonzero defaults 100m / 200Mi on a 1-CPU / 1-Gi
        # node: f_cpu = 100/1000 = 0.1, f_mem = 200Mi/1Gi = 0.1953125
        # std = 0.04765625 -> int(95.234375) = 95
        "name": "nonzero-defaults",
        "node_cpu_milli": 1000,
        "node_mem": GI,
        "pod_cpu_milli": None,
        "pod_mem": None,
        "want": 95,
    },
]

# ---------------------------------------------------------------------------
# NodeResourcesFit score = LeastAllocated
# (noderesources/resource_allocation.go + least_allocated.go,
#  leastResourceScorer): per resource (weight 1 each for cpu/memory):
#    score_r = ((allocatable - requested) * 100) / allocatable   [int64 div]
#    0 when requested > allocatable
#  node score = sum(score_r * w_r) / sum(w_r)                    [int64 div]
# ---------------------------------------------------------------------------

LEAST_ALLOCATED_CASES = [
    {
        # cpu (4000-1000)*100/4000 = 75; mem (10000-2000)*100/10000 = 80
        # (75 + 80) / 2 = 77  [integer division]
        "name": "light-load",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 1000,
        "pod_mem": 2000,
        "want": 77,
    },
    {
        # cpu (1000*100)/4000 = 25; mem (5000*100)/10000 = 50 -> 75/2 = 37
        "name": "heavy-load",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 3000,
        "pod_mem": 5000,
        "want": 37,
    },
    {
        # cpu requested 5000 > allocatable 4000 -> 0; mem 50 -> 50/2 = 25
        "name": "over-requested-cpu-scores-zero",
        "node_cpu_milli": 4000,
        "node_mem": 10000,
        "pod_cpu_milli": 5000,
        "pod_mem": 5000,
        "want": 25,
    },
    {
        # nonzero defaults on 1 CPU / 1 Gi:
        # cpu (1000-100)*100/1000 = 90
        # mem (1073741824-209715200)*100/1073741824 = 86402662400/1073741824
        #     = 80  [floor of 80.468...]
        # (90 + 80) / 2 = 85
        "name": "nonzero-defaults",
        "node_cpu_milli": 1000,
        "node_mem": GI,
        "pod_cpu_milli": None,
        "pod_mem": None,
        "want": 85,
    },
]

# ---------------------------------------------------------------------------
# TaintToleration score
# (tainttoleration/taint_toleration.go): raw score = count of the node's
# PreferNoSchedule taints the pod does NOT tolerate; NormalizeScore =
# helper.DefaultNormalizeScore(100, reverse=true):
#    max = max(raw); normalized_i = 100 - (100 * raw_i / max)  [int64 div]
#    (all 100 when max == 0)
# ---------------------------------------------------------------------------

# Node i carries i PreferNoSchedule taints, pod tolerates none:
# raw = [0, 1, 2]; max = 2
# normalized = [100 - 0, 100 - 100*1/2, 100 - 100*2/2] = [100, 50, 0]
TAINT_PREFER_COUNTS = [0, 1, 2]
TAINT_EXPECT_RAW = [0, 1, 2]
TAINT_EXPECT_NORMALIZED = [100, 50, 0]

# ---------------------------------------------------------------------------
# ImageLocality
# (imagelocality/image_locality.go): for each container whose image the
# node holds: scaledImageScore = int64(size * (numNodesWithImage /
# totalNumNodes)); sumScores over containers; then calculatePriority:
#    minThreshold = 23 MB, maxThreshold = 1000 MB * numContainers
#    clamped = clamp(sumScores, minThreshold, maxThreshold)
#    score = int64(100 * (clamped - minThreshold) / (maxThreshold - minThreshold))
# ---------------------------------------------------------------------------

IMAGE_LOCALITY_CASES = [
    {
        # 2 nodes; only node-a holds img-big (300 MB, numNodes=1):
        # scaled = int(300MB * 1/2) = 150 MB
        # node-a: 100 * (150-23)MB / (1000-23)MB = 12700/977 = 12.99 -> 12
        # node-b: sum 0 -> clamps to minThreshold -> 0
        "name": "single-container-half-spread",
        "images": {"img-big": {"size": 300 * MB, "on": ["node-a"]}},
        "pod_images": ["img-big"],
        "want": {"node-a": 12, "node-b": 0},
    },
    {
        # img-everywhere 500 MB on both nodes (numNodes=2, scaled 500 MB),
        # img-rare 200 MB only on node-a (scaled 100 MB); 2 containers:
        # node-a: sum = 600 MB; maxThreshold = 2000 MB
        #   100 * (600-23) / (2000-23) = 57700/1977 = 29.18 -> 29
        # node-b: sum = 500 MB -> 100 * 477/1977 = 24.12 -> 24
        "name": "two-containers-mixed-spread",
        "images": {
            "img-everywhere": {"size": 500 * MB, "on": ["node-a", "node-b"]},
            "img-rare": {"size": 200 * MB, "on": ["node-a"]},
        },
        "pod_images": ["img-everywhere", "img-rare"],
        "want": {"node-a": 29, "node-b": 24},
    },
]

# ---------------------------------------------------------------------------
# PodTopologySpread filter (podtopologyspread/filtering.go):
# For each DoNotSchedule constraint: matchNum(domain) = count of existing
# pods matching the labelSelector in that topology domain;
# minMatch = min over all domains present among eligible nodes;
# candidate node violates iff matchNum(node's domain) + 1 - minMatch > maxSkew.
# Nodes missing the topology key always fail that constraint.
#
# The incoming pod is itself labeled foo=bar, so selfMatchNum = 1
# (upstream filtering.go: skew = matchNum + selfMatchNum - minMatchNum).
#
# Topology: zone1 = {node-a, node-b}, zone2 = {node-x, node-y}; every node
# also has its own hostname label.  Existing pods labeled foo=bar: 2 on
# node-a, 0 elsewhere.
#
# zone-only constraint (maxSkew=1): domains zone1=2, zone2=0, min=0
#   node-a/node-b: 2+1-0 = 3 > 1 -> violate; node-x/node-y: 0+1-0 = 1 -> ok
# hostname-only constraint (maxSkew=1): domains a=2 b=0 x=0 y=0, min=0
#   node-a: 2+1-0 = 3 > 1 -> violate; b/x/y: 0+1-0 = 1 -> ok
#   (node-b passes here but fails the zone constraint — the two
#   constraints are distinguishable.)
# ---------------------------------------------------------------------------

SPREAD_EXISTING = {"node-a": 2, "node-b": 0, "node-x": 0, "node-y": 0}
SPREAD_ZONE_ONLY_EXPECT = {  # True = violates
    "node-a": True,
    "node-b": True,
    "node-x": False,
    "node-y": False,
}
SPREAD_HOSTNAME_ONLY_EXPECT = {
    "node-a": True,
    "node-b": False,
    "node-x": False,
    "node-y": False,
}
SPREAD_BOTH_EXPECT = {
    "node-a": True,
    "node-b": True,
    "node-x": False,
    "node-y": False,
}

# ScheduleAnyway scoring is ordinal here (the v1.30 scoring internals
# carry normalizing weights; the ordering over domains is the contract):
# fewer matching pods in the candidate's domain => strictly higher score.
# hostname counts a=2, b=1, x=y=0  ->  score(x) == score(y) > score(b) > score(a)
SPREAD_SCORE_EXISTING = {"node-a": 2, "node-b": 1, "node-x": 0, "node-y": 0}

# ---------------------------------------------------------------------------
# InterPodAffinity (interpodaffinity/filtering.go + scoring.go):
# required podAffinity: candidate node's topology domain must already hold
#   a pod matching the term (or the incoming pod may match its own term's
#   selector+namespace when the domain holds no pod at all — the
#   first-pod-of-series escape).
# required podAntiAffinity: candidate's domain must hold NO matching pod;
#   symmetric: an existing pod's required anti-affinity term matching the
#   incoming pod blocks that existing pod's domain.
# preferred scoring: for each existing pod and each weighted term of the
#   incoming pod that matches it, every node in the existing pod's domain
#   gains the weight; NormalizeScore scales linearly so max -> 100, min -> 0:
#     normalized_i = int(100 * (raw_i - min) / (max - min))   [float64]
# ---------------------------------------------------------------------------

# Topology again zone1={node-a,node-b}, zone2={node-x,node-y}.
# Existing: app=db pod on node-a.
# Incoming requires podAffinity to app=db over "zone":
IPA_REQUIRED_AFFINITY_EXPECT = {
    "node-a": True,  # zone1 holds the db pod
    "node-b": True,
    "node-x": False,
    "node-y": False,
}
# Existing: app=web pod on node-x.  Incoming requires podAntiAffinity to
# app=web over "zone":
IPA_REQUIRED_ANTI_EXPECT = {
    "node-a": True,
    "node-b": True,
    "node-x": False,
    "node-y": False,
}
# Existing pod on node-b carries required anti-affinity to team=t1 over
# "hostname"; incoming pod is labeled team=t1: only node-b is blocked.
IPA_EXISTING_ANTI_EXPECT = {
    "node-a": True,
    "node-b": False,
    "node-x": True,
    "node-y": True,
}
# Preferred affinity weight 5 to app=db over "zone", db pod on node-a:
# raw = [5, 5, 0, 0] -> min 0, max 5 -> normalized [100, 100, 0, 0]
IPA_PREFERRED_WEIGHT = 5
IPA_PREFERRED_EXPECT_NORMALIZED = {
    "node-a": 100,
    "node-b": 100,
    "node-x": 0,
    "node-y": 0,
}
