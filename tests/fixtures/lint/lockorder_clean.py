"""Declared, acyclic lock nesting — zero lock-order findings
(tests/test_lint.py).

NOT imported by anything.  ``Store.txn`` holds its RLock across a call
into ``Plane.poke`` (receiver typed by the ``__init__`` parameter
annotation); the nesting is declared, and the nested RE-acquisition of
the RLock in ``_locked_size`` pins the reentrant-self-deadlock
exemption for RLock domains.
"""

import threading


class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = 0  # guarded-by: _lock

    def poke(self):
        with self._lock:
            self.events += 1


# ksimlint: lock-order(Store._lock<Plane._lock)
class Store:
    def __init__(self, plane: "Plane"):
        self._lock = threading.RLock()
        self.plane = plane
        self.size = 0  # guarded-by: _lock

    def _locked_size(self):
        with self._lock:  # reentrant: fine, _lock is an RLock
            return self.size

    def txn(self):
        with self._lock:
            self.plane.poke()
            return self._locked_size()
