"""Role-annotated threads with no violations (tests/test_lint.py).

NOT imported by anything.  The worker only READS off-main (snapshot
tearing is tolerated); the main-thread-pinned ``apply_result`` is
never called from the worker's reachable set.
"""

import threading


class Driver:
    def __init__(self):
        self.applied = 0  # guarded-by: main-thread

    def start(self):
        threading.Thread(target=self._work, daemon=True).start()

    def _work(self):  # ksimlint: thread-role(dispatch-worker)
        return self._peek()

    def _peek(self):
        return self.applied  # off-main read: tolerated

    def apply_result(self):  # ksimlint: thread-role(main-thread)
        self.applied = 1
