"""A pure kernel: zero findings expected.  Static-arg branching,
shape arithmetic, comprehension filters and nested bodies are all
legal trace-time Python."""


def device_kernel(fn=None, *, static=()):
    return fn if fn is not None else (lambda f: f)


@device_kernel(static=("st", "prog"))
def pure_kernel(st, prog, const, ev, state0):
    import jax
    import jax.numpy as jnp

    n_scores = sum(1 for p in prog.plugins if p.enabled)
    width = const["rows"].shape[0]
    if st.record == "full":  # static branch: fine
        extra = jnp.zeros((n_scores, width), jnp.int32)
    else:
        extra = jnp.zeros((0, width), jnp.int32)

    def step(carry, e):
        nxt = jnp.where(e >= 0, carry + e, carry)
        return nxt, nxt

    final, outs = jax.lax.scan(step, state0, ev)
    return final, outs, extra
