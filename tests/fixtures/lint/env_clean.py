"""Reads only variables documented in env_docs.md."""

import os

FLAG = os.environ.get("KSIM_LINTFIXTURE_DOCUMENTED", "") == "1"
