"""Undeclared lock nesting with an inline waiver (tests/test_lint.py).

NOT imported by anything.  ``nest`` acquires ``_inner`` under
``_outer`` without a ``lock-order`` declaration; the ``disable``
comment on the acquisition line suppresses the finding AND — because
every witness of the edge is suppressed — waives the edge out of the
cycle graph (tools/ksimlint/rules/lock_order.py).
"""

import threading


class Holder:
    def __init__(self):
        self._outer = threading.Lock()
        self._inner = threading.Lock()

    def nest(self):
        with self._outer:
            with self._inner:  # ksimlint: disable=lock-order
                return 1
