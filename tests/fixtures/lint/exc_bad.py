"""Seeded exception-flow violation (tests/test_lint.py).

NOT imported by anything.  ``run_all``'s broad handler absorbs the
RunCancelled that ``_step`` may raise (visible only through the call
graph) without an ``except RunCancelled: raise`` arm and without
re-raising or capturing the bound exception — the one expected
finding.
"""


class RunCancelled(BaseException):
    pass


def _step():
    raise RunCancelled()


def run_all():
    try:
        _step()
    except Exception:
        return None
