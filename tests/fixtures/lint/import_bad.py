"""Seeded import-boundary violations: module-scope accelerator import
in a file the test declares stdlib-only, plus a parent-side function
importing jax (only child*/_child* payloads may)."""

import json  # stdlib: fine
import numpy as np  # finding under import-time AND parent-child scopes


def parent_helper():
    import jax  # finding under parent-child scope

    return jax, np, json


def child_payload():
    import jax  # sanctioned: child payload, subprocess-only

    return jax
