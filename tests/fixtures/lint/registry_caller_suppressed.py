"""The same rogue literals, inline-suppressed."""


class _Stub:
    def check(self, site):
        pass

    def event(self, name, **kw):
        pass


FAULTS = _Stub()
TRACE = _Stub()


def run():
    FAULTS.check("rogue.site")  # ksimlint: disable=registry-literals
    TRACE.event("rogue.event")  # ksimlint: disable=registry-literals
