"""Stdlib-only module: lazy accelerator imports inside functions are
legal under the import-time scope (that is the guarded-bridge idiom),
and child payloads may import anything."""

import json
import os
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import numpy  # annotation-only: never executes at import time


def lazy_bridge():
    try:
        import jax

        return jax
    except Exception:
        return None


def _child_payload():
    import numpy as np

    return np, json, os
