"""Disciplined version of lock_bad: zero findings expected."""

import threading

_registry = {}  # guarded-by: _registry_lock
_registry_lock = threading.Lock()


def get_entry(name):
    with _registry_lock:
        return _registry.get(name)


class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self.counter = 0  # guarded-by: main-thread

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def _merge(self, xs):  # ksimlint: lock-held(_lock)
        self._items.extend(xs)

    def snapshot(self):
        with self._lock:
            return list(self._items)

    def _run(self):  # ksimlint: worker-thread
        return self.counter + 1  # reads are fine; no writes
