"""Seeded trace-ingest violation (tests/test_lint.py).

NOT imported by anything.  The ``trace-ingest`` producer reaches
``_account`` along a same-receiver edge, and ``_account`` WRITES a
``# guarded-by: main-thread`` attribute — the one expected finding
(cross-thread write; reads would be tolerated).
"""

import threading


class Producer:
    def __init__(self):
        self.consumed = 0  # guarded-by: main-thread

    def start(self):
        threading.Thread(target=self._produce, daemon=True).start()

    def _produce(self):  # ksimlint: thread-role(trace-ingest)
        self._account()

    def _account(self):
        self.consumed += 1  # cross-thread write: the seeded finding
