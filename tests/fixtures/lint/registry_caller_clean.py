"""Every literal resolves into its registry: zero findings expected
(together with registry_replay_clean.py as the replay module)."""


class _Stub:
    def check(self, site):
        pass

    def span(self, name, **kw):
        pass

    def event(self, name, **kw):
        pass


FAULTS = _Stub()
TRACE = _Stub()


def run():
    with_span = TRACE.span("wired.site")
    FAULTS.check("wired.site")
    TRACE.event("fault.fired", site="wired.site")
    return with_span
