"""Seeded lock-discipline violations (tests/test_lint.py).

NOT imported by anything — the analyzer reads it as text.  Expected
findings: the unlocked read in ``bad_read``, the unlocked write in
``bad_write``, the closure escape in ``bad_closure``, the module-global
access in ``bad_global``, and the worker-thread self-write in ``_run``.
"""

import threading

_registry = {}  # guarded-by: _registry_lock
_registry_lock = threading.Lock()


def bad_global(name):
    return _registry.get(name)  # unlocked module-global access


class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self.counter = 0  # guarded-by: main-thread

    def good(self, x):
        with self._lock:
            self._items.append(x)

    def good_held(self):  # ksimlint: lock-held(_lock)
        return len(self._items)

    def bad_read(self):
        return list(self._items)  # unlocked read

    def bad_write(self, x):
        self._items.append(x)  # unlocked write

    def bad_closure(self):
        with self._lock:
            def peek():
                return self._items  # closure may outlive the with block

            return peek

    def _run(self):  # ksimlint: worker-thread
        self.counter += 1  # workers must not write driver state
