"""Broad RunCancelled absorption with an inline waiver
(tests/test_lint.py).

NOT imported by anything.  Same shape as exc_bad.py; the ``disable``
comment on the handler line records a justified exception.
"""


class RunCancelled(BaseException):
    pass


def _step():
    raise RunCancelled()


def run_all():
    try:
        _step()
    except Exception:  # ksimlint: disable=exception-flow
        return None
