"""registry_regs.py with one METRIC_NAMES entry that has no
``_expo_family`` declaration — the dead-registry-entry direction."""

SITES: tuple = ("wired.site",)

SPAN_NAMES: tuple = ("wired.site", "other.span")

EVENT_NAMES: tuple = ("fault.fired", "replay.fallback", "other.event")

METRIC_NAMES: tuple = ("ksim_wired_total", "ksim_dead_total")


def _expo_family(name, kind, help_):
    return {"name": name, "kind": kind, "help": help_}


_FAMILIES = (_expo_family("ksim_wired_total", "counter", "wired family"),)
