"""Reads an env var missing from the fixture docs table."""

import os

FLAG = os.environ.get("KSIM_LINTFIXTURE_UNDOCUMENTED", "") == "1"
