"""Stands in for engine/replay.py in the bad registry fixture: one
unregistered call reason, one dead registry entry, one unregistered
f-string family."""

FALLBACK_REASONS: frozenset = frozenset({"known_reason", "dead_entry"})

FALLBACK_REASON_PREFIXES: tuple = ("op:",)


class Driver:
    def _reject(self, reason):
        pass

    def lower(self, op):
        self._reject("rogue_reason")  # finding: not in FALLBACK_REASONS
        self._reject(f"host_hook:{op}")  # finding: family not in PREFIXES
        self._reject(f"op:{op}")  # registered family: fine
        return "known_reason"  # keeps known_reason alive; dead_entry is not
