"""Seeded kernel-purity violations (tests/test_lint.py).

The decorator is a local stub: the analyzer matches it by NAME in the
AST, and this file is never imported.  Expected findings: traced
branch, print, host coercion, numpy-on-traced, 64-bit dtype, .item(),
and the traced branch inside the nested scan body.
"""


def device_kernel(fn=None, *, static=()):
    return fn if fn is not None else (lambda f: f)


@device_kernel(static=("cfg",))
def impure_kernel(cfg, state, ev):
    import numpy as np

    if cfg.preempt:  # static: NOT a finding
        pass
    if state > 0:  # finding: traced branch
        print("debug")  # finding: host print
    total = float(state)  # finding: host coercion
    host = np.sum(ev)  # finding: numpy on a traced value
    wide = ev.astype("float64")  # finding: 64-bit dtype literal
    scalar = ev.item()  # finding: host sync

    def body(carry, x):
        if x:  # finding: traced branch in a scan body
            return carry
        return carry

    return total, host, wide, scalar, body
