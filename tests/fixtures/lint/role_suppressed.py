"""Worker-reachable store with an inline waiver (tests/test_lint.py).

NOT imported by anything.  Same shape as role_bad.py; the ``disable``
comment on the store line records a justified exception (the
fleet-driver lazy-mesh pattern: a lock-guarded write that MUST happen
on the worker so a wedged backend hangs the watchdogged thread).
"""

import threading


class Driver:
    def __init__(self):
        self.done = 0

    def start(self):
        threading.Thread(target=self._work, daemon=True).start()

    def _work(self):  # ksimlint: thread-role(dispatch-worker)
        self._apply()

    def _apply(self):
        self.done = 1  # ksimlint: disable=thread-role
