"""Call sites with out-of-registry literals and one dynamic name.
``FAULTS`` / ``TRACE`` are local stubs — the analyzer matches the
call shape, the file is never imported."""


class _Stub:
    def check(self, site):
        pass

    def span(self, name, **kw):
        pass

    def event(self, name, **kw):
        pass


FAULTS = _Stub()
TRACE = _Stub()


def _expo_family(name, kind, help_):
    return {}


_ROGUE = _expo_family("rogue_metric", "counter", "x")  # finding: not in METRIC_NAMES


def run(name):
    FAULTS.check("rogue.site")  # finding: not in SITES
    TRACE.span("rogue.span")  # finding: not in SPAN_NAMES
    TRACE.event("rogue.event")  # finding: not in EVENT_NAMES
    TRACE.event(name)  # finding: non-literal name
    _expo_family(name, "counter", "x")  # finding: non-literal family
