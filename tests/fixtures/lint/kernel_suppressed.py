"""A kernel-purity violation with an inline suppression."""


def device_kernel(fn=None, *, static=()):
    return fn if fn is not None else (lambda f: f)


@device_kernel
def debug_kernel(state):
    # Temporary trace-time diagnostic, runs once per compile only.
    print("tracing", state.shape)  # ksimlint: disable=kernel-purity
    return state
