"""The same undocumented read, inline-suppressed (plus a documented
read so the clean docs table has no dead row in this project)."""

import os

FLAG = os.environ.get("KSIM_LINTFIXTURE_UNDOCUMENTED", "") == "1"  # ksimlint: disable=env-contract
DOCUMENTED = os.environ.get("KSIM_LINTFIXTURE_DOCUMENTED", "") == "1"
