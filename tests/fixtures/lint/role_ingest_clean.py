"""trace-ingest role with no violations (tests/test_lint.py).

NOT imported by anything.  Mirrors ksim_tpu/traces/stream.py's shape:
the producer thread carries the ``trace-ingest`` role, writes only its
OWN (unguarded) stats attributes, and READS a main-thread-guarded
counter for its progress line — off-main reads tolerate tearing.
"""

import queue
import threading


class Producer:
    def __init__(self):
        self.windows = 0  # producer-owned stat: unguarded by design
        self.consumed = 0  # guarded-by: main-thread
        self._q = queue.Queue(maxsize=4)

    def start(self):
        threading.Thread(target=self._produce, daemon=True).start()

    def _produce(self):  # ksimlint: thread-role(trace-ingest)
        for win in self._windows():
            self._q.put(win)
            self.windows += 1

    def _windows(self):
        _ = self.consumed  # off-main read: tolerated
        yield []

    def drain(self):  # ksimlint: thread-role(main-thread)
        self.consumed += 1
        return self._q.get_nowait()
