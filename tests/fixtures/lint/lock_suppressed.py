"""Same violation as lock_bad.bad_read, inline-suppressed."""

import threading


class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock

    def racy_size_hint(self):
        # Benign approximate read, documented as such.
        return len(self._items)  # ksimlint: disable=lock-discipline
