"""Seeded lock-order deadlock (tests/test_lint.py).

NOT imported by anything — the analyzer reads it as text.  BOTH
nestings are DECLARED below, so neither edge is an undeclared-nesting
finding; the one expected finding is the cycle: ``take_ab`` holds
``_a`` across a call that acquires ``_b`` while ``take_ba`` holds
``_b`` across a call that acquires ``_a`` — the classic ABBA deadlock,
visible only interprocedurally (neither function nests two ``with``
blocks lexically).
"""

import threading


# Two declarations that together ARE a deadlock — the analyzer must
# reject the pair, not trust them individually:
# ksimlint: lock-order(Pair._a<Pair._b)
# ksimlint: lock-order(Pair._b<Pair._a)
class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def _grab_a(self):
        with self._a:
            return "a"

    def _grab_b(self):
        with self._b:
            return "b"

    def take_ab(self):
        with self._a:
            return self._grab_b()

    def take_ba(self):
        with self._b:
            return self._grab_a()
