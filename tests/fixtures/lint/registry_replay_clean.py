"""Clean replay-module stand-in: every reason registered and alive."""

FALLBACK_REASONS: frozenset = frozenset({"known_reason"})

FALLBACK_REASON_PREFIXES: tuple = ("op:",)


class Driver:
    def _reject(self, reason):
        pass

    def lower(self, op):
        self._reject("known_reason")
        self._reject(f"op:{op}")
