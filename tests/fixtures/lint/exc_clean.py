"""Compliant exception flow — zero exception-flow findings
(tests/test_lint.py).

NOT imported by anything.  Pins every compliant shape: an explicit
``except RunCancelled`` arm above the broad ladder, the
capture-for-the-caller box pattern, and ReplayFallback raised only
inside a ``_reject`` constructor.
"""


class RunCancelled(BaseException):
    pass


class ReplayFallback(Exception):
    pass


def _step():
    raise RunCancelled()


def guarded():
    try:
        _step()
    except RunCancelled:
        raise
    except Exception:
        return None


def captured(box):
    try:
        _step()
    except BaseException as e:
        box["err"] = e


def _reject(reason):
    raise ReplayFallback(reason)
