"""A module-scope forbidden import with an inline suppression."""

# Optional-at-import contract documented here.
import numpy  # ksimlint: disable=import-boundary

_ = numpy
