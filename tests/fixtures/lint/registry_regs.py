"""Mini registries for the registry-literals fixture tests: stands in
for faults.py (SITES) and obs.py (SPAN_NAMES / EVENT_NAMES /
METRIC_NAMES).  The wired exposition family is declared here too —
mirroring the real obs.py, where the ``_expo_family`` calls live in
the registry module itself."""

SITES: tuple = ("wired.site",)

SPAN_NAMES: tuple = ("wired.site", "other.span")

EVENT_NAMES: tuple = ("fault.fired", "replay.fallback", "other.event")

METRIC_NAMES: tuple = ("ksim_wired_total",)


def _expo_family(name, kind, help_):
    return {"name": name, "kind": kind, "help": help_}


_FAMILIES = (_expo_family("ksim_wired_total", "counter", "wired family"),)
