"""Mini registries for the registry-literals fixture tests: stands in
for faults.py (SITES) and obs.py (SPAN_NAMES / EVENT_NAMES)."""

SITES: tuple = ("wired.site",)

SPAN_NAMES: tuple = ("wired.site", "other.span")

EVENT_NAMES: tuple = ("fault.fired", "replay.fallback", "other.event")
