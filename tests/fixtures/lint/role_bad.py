"""Seeded thread-role violation (tests/test_lint.py).

NOT imported by anything.  ``_work`` carries the dispatch-worker role;
``_apply`` is reachable from it along a same-receiver edge and stores
to ``self`` — the one expected finding.  The round-8 lexical check
cannot see it (the store is not IN the annotated function), which is
exactly what the interprocedural propagation adds.
"""

import threading


class Driver:
    def __init__(self):
        self.done = 0

    def start(self):
        threading.Thread(target=self._work, daemon=True).start()

    def _work(self):  # ksimlint: thread-role(dispatch-worker)
        self._apply()

    def _apply(self):
        self.done = 1  # worker-reachable store: the seeded finding
