"""Hand-derived preemption victim-search fixtures.

Each case is worked out BY HAND from the upstream algorithm definitions
(selectVictimsOnNode's remove-all / reprieve-in-MoreImportantPod-order
loop, pickOneNodeForPreemption's narrowing criteria, and
PodEligibleToPreemptOthers) — never by running this repo's oracle or
kernels (repo CLAUDE.md: fixtures are the independent side of parity).
The arithmetic is single-resource CPU so every fit check is checkable in
one's head; the derivation for each case is in its comment.

Consumed by tests/test_preemption_fixtures.py (host oracle path) and
tests/test_replay_device.py (on-device victim search) — both must land
on the same nominated node and the same victims IN THE SAME ORDER
(victims are appended in reprieve = MoreImportantPod order: higher
priority first, then earlier start time).
"""

from __future__ import annotations

# Node: (name, cpu).  Victim: (name, node, cpu, priority, start_time or
# None -> no status.startTime, creationTimestamp is the fallback).
# Preemptor: (cpu, priority, preemptionPolicy or None).
# expected_nominated: node name or None.
# expected_victims: names in eviction (reprieve) order.
CASES = [
    {
        # Node full: 4 x 1cpu victims prio 1..4; preemptor needs 2.
        # Remove all -> 4 free, fits.  Reprieve most-important first:
        # +prio4 (3 free >= 2, stays), +prio3 (2 free >= 2, stays),
        # +prio2 (1 free < 2, VICTIM), +prio1 (1 free < 2, VICTIM).
        "name": "reprieve_minimal_set",
        "nodes": [("n0", "4")],
        "victims": [
            ("v1", "n0", "1", 1, None),
            ("v2", "n0", "1", 2, None),
            ("v3", "n0", "1", 3, None),
            ("v4", "n0", "1", 4, None),
        ],
        "preemptor": ("2", 10, None),
        "expected_nominated": "n0",
        "expected_victims": ["v2", "v1"],
    },
    {
        # Equal priorities: MoreImportantPod falls to start time, the
        # EARLIER-started pod is more important.  Node cpu 3, three
        # 1cpu victims prio 5 started Jan/Feb/Mar; preemptor needs 1.
        # Reprieve order Jan, Feb, Mar: +Jan (2 free), +Feb (1 free),
        # +Mar (0 free < 1, VICTIM).
        "name": "start_time_reprieve_order",
        "nodes": [("n0", "3")],
        "victims": [
            ("mar", "n0", "1", 5, "2026-03-01T00:00:00Z"),
            ("jan", "n0", "1", 5, "2026-01-01T00:00:00Z"),
            ("feb", "n0", "1", 5, "2026-02-01T00:00:00Z"),
        ],
        "preemptor": ("1", 10, None),
        "expected_nominated": "n0",
        "expected_victims": ["mar"],
    },
    {
        # preemptionPolicy=Never opts the preemptor out entirely, even
        # with an otherwise-perfect candidate available.
        "name": "preemption_policy_never",
        "nodes": [("n0", "1")],
        "victims": [("low", "n0", "1", 1, None)],
        "preemptor": ("1", 10, "Never"),
        "expected_nominated": None,
        "expected_victims": [],
    },
    {
        # pickOneNode criterion 1: lowest highest-victim priority.
        # Both nodes need their single victim evicted; a's victim has
        # priority 2 < b's 8.
        "name": "pick_lowest_top_priority",
        "nodes": [("a", "1"), ("b", "1")],
        "victims": [
            ("va", "a", "1", 2, None),
            ("vb", "b", "1", 8, None),
        ],
        "preemptor": ("1", 10, None),
        "expected_nominated": "a",
        "expected_victims": ["va"],
    },
    {
        # Criterion 2: highest priorities tie (3 == 3), priority sums
        # decide: a = 3+1 = 4 < b = 3+2 = 5.  Preemptor needs the whole
        # node (cpu 2 of 2), so both victims fall on each node.
        "name": "pick_smallest_priority_sum",
        "nodes": [("a", "2"), ("b", "2")],
        "victims": [
            ("a-hi", "a", "1", 3, None),
            ("a-lo", "a", "1", 1, None),
            ("b-hi", "b", "1", 3, None),
            ("b-lo", "b", "1", 2, None),
        ],
        "preemptor": ("2", 10, None),
        "expected_nominated": "a",
        "expected_victims": ["a-hi", "a-lo"],
    },
    {
        # Criterion 4: priorities, sums and counts all tie; the node
        # whose highest-priority victim started LATEST (did the least
        # work) wins -> b (June > January).
        "name": "pick_latest_top_priority_start",
        "nodes": [("a", "1"), ("b", "1")],
        "victims": [
            ("va", "a", "1", 5, "2026-01-01T00:00:00Z"),
            ("vb", "b", "1", 5, "2026-06-01T00:00:00Z"),
        ],
        "preemptor": ("1", 10, None),
        "expected_nominated": "b",
        "expected_victims": ["vb"],
    },
    {
        # startTime fallback: no status.startTime anywhere, so the
        # comparison runs on creationTimestamps (set per victim by the
        # harness from `created`); b's victim was created later ->
        # latest earliest-top-start -> b.
        "name": "start_time_falls_back_to_creation",
        "nodes": [("a", "1"), ("b", "1")],
        "victims": [
            ("va", "a", "1", 5, None, "2026-01-01T00:00:00Z"),
            ("vb", "b", "1", 5, None, "2026-02-01T00:00:00Z"),
        ],
        "preemptor": ("1", 10, None),
        "expected_nominated": "b",
        "expected_victims": ["vb"],
    },
]
