"""KEP-140 Scenario document loading + patch/done replay semantics."""

import pytest

from ksim_tpu.scenario import (
    ScenarioRunner,
    ScenarioSpecError,
    load_scenario,
    operations_from_spec,
)
from ksim_tpu.scenario.spec import merge_patch
from tests.helpers import make_node, make_pod


def scenario_doc():
    return {
        "apiVersion": "simulation.sigs.x-k8s.io/v1alpha1",
        "kind": "Scenario",
        "metadata": {"name": "s1"},
        "spec": {
            "operations": [
                {
                    "id": "create-node",
                    "step": 0,
                    "createOperation": {"object": {"kind": "Node", **make_node("n1", cpu="8")}},
                },
                {
                    "id": "create-pod",
                    "step": 1,
                    "createOperation": {"object": {"kind": "Pod", **make_pod("p1", cpu="1")}},
                },
                {
                    "id": "label-node",
                    "step": 2,
                    "patchOperation": {
                        "typeMeta": {"kind": "Node"},
                        "objectMeta": {"name": "n1"},
                        "patch": '{"metadata": {"labels": {"zone": "a"}}}',
                    },
                },
                {"id": "finish", "step": 3, "doneOperation": {}},
                {
                    "id": "never-runs",
                    "step": 4,
                    "deleteOperation": {
                        "typeMeta": {"kind": "Node"},
                        "objectMeta": {"name": "n1"},
                    },
                },
            ]
        },
    }


def test_operations_from_spec_shapes():
    ops = operations_from_spec(scenario_doc())
    assert [o.op for o in ops] == ["create", "create", "patch", "done", "delete"]
    assert ops[0].kind == "nodes" and ops[1].kind == "pods"
    assert ops[2].name == "n1" and ops[2].obj == {"metadata": {"labels": {"zone": "a"}}}


def test_yaml_round_trip():
    import yaml

    ops = load_scenario(yaml.safe_dump(scenario_doc()))
    assert len(ops) == 5


def test_replay_applies_patch_and_stops_at_done():
    runner = ScenarioRunner()
    res = runner.run(operations_from_spec(scenario_doc()))
    assert res.succeeded
    # done at step 3 halts before the delete at step 4.
    assert [s.step for s in res.steps] == [0, 1, 2, 3]
    node = runner.store.get("nodes", "n1")
    assert node["metadata"]["labels"]["zone"] == "a"
    assert res.pods_scheduled == 1
    pod = runner.store.list("pods")[0]
    assert pod["spec"]["nodeName"] == "n1"


def test_invalid_operations_rejected():
    with pytest.raises(ScenarioSpecError):
        operations_from_spec({"spec": {"operations": [{"id": "x", "step": 0}]}})
    with pytest.raises(ScenarioSpecError):
        operations_from_spec(
            {"spec": {"operations": [
                {"step": 0, "createOperation": {"object": {"kind": "Pod"}},
                 "doneOperation": {}},
            ]}}
        )
    with pytest.raises(ScenarioSpecError):
        operations_from_spec(
            {"spec": {"operations": [
                {"step": 0, "createOperation": {"object": {"kind": "Gadget",
                                                           "metadata": {"name": "g"}}}},
            ]}}
        )
    with pytest.raises(ScenarioSpecError):
        operations_from_spec({})


def test_merge_patch_rfc7386():
    target = {"a": {"b": 1, "c": 2}, "d": [1, 2]}
    patch = {"a": {"b": None, "e": 3}, "d": [9]}
    assert merge_patch(target, patch) == {"a": {"c": 2, "e": 3}, "d": [9]}


def test_scheduler_simulation_document(tmp_path):
    """KEP-184 one-shot run: simulator spec + scenario file -> status +
    result file (keps/184-scheduler-simulation/README.md)."""
    import json
    import yaml

    from ksim_tpu.scenario.simulation import run_scheduler_simulation

    scenario_path = tmp_path / "scenario.yaml"
    scenario_path.write_text(yaml.safe_dump(scenario_doc()))
    result_path = tmp_path / "result.json"
    doc = {
        "kind": "SchedulerSimulation",
        "metadata": {"name": "sim1"},
        "spec": {
            "simulator": {
                "schedulerConfig": {"profiles": [{"plugins": {"multiPoint": {
                    "disabled": [{"name": "InterPodAffinity"}]}}}]},
                "recordMode": "full",
            },
            "scenarioTemplateFilePath": str(scenario_path),
            "scenarioResultFilePath": str(result_path),
        },
    }
    out = run_scheduler_simulation(doc)
    assert out["status"]["phase"] == "Succeeded"
    assert out["status"]["result"]["podsScheduled"] == 1
    stored = json.loads(result_path.read_text())
    assert stored["status"]["result"]["eventsApplied"] == 4


def test_scheduler_simulation_failure_phase():
    from ksim_tpu.scenario.simulation import run_scheduler_simulation

    out = run_scheduler_simulation({
        "spec": {
            "scenario": {"spec": {"operations": [
                {"step": 0, "deleteOperation": {
                    "typeMeta": {"kind": "Node"},
                    "objectMeta": {"name": "missing"}}},
            ]}},
        }
    })
    assert out["status"]["phase"] == "Failed"
    assert "NotFound" in out["status"]["message"]


# ---------------------------------------------------------------------------
# Round 14: sourced scenarios (source.trace) + the spec faults section
# ---------------------------------------------------------------------------


def _trace_source_doc(**trace):
    return {"spec": {"source": {"trace": trace}}}


def test_source_trace_compiles_operations(monkeypatch):
    monkeypatch.setenv("KSIM_TRACES_DIR", "tests/fixtures/traces")
    ops = operations_from_spec(
        _trace_source_doc(
            name="borg_mini.jsonl", format="borg", nodes=8, opsPerStep=4
        )
    )
    assert sum(1 for o in ops if o.kind == "nodes" and o.op == "create") == 8
    assert all(o.op in ("create", "delete") for o in ops)
    # Same doc -> same stream (the determinism guarantee the behavior
    # locks ride on).
    again = operations_from_spec(
        _trace_source_doc(
            name="borg_mini.jsonl", format="borg", nodes=8, opsPerStep=4
        )
    )
    assert ops == again


def test_source_trace_path_resolver_for_library_use():
    ops = operations_from_spec(
        _trace_source_doc(
            path="tests/fixtures/traces/alibaba_batch_mini.csv",
            format="alibaba",
            nodes=4,
        )
    )
    assert sum(1 for o in ops if o.kind == "pods" and o.op == "create") == 24


def test_source_trace_refusals(monkeypatch):
    monkeypatch.delenv("KSIM_TRACES_DIR", raising=False)
    with pytest.raises(ScenarioSpecError, match="no trace registry"):
        operations_from_spec(_trace_source_doc(name="x.jsonl", format="borg"))
    with pytest.raises(ScenarioSpecError, match="format"):
        operations_from_spec(_trace_source_doc(name="x.jsonl", format="nope"))
    with pytest.raises(ScenarioSpecError, match="needs a name"):
        operations_from_spec(_trace_source_doc(format="borg"))
    with pytest.raises(ScenarioSpecError, match="exactly one"):
        operations_from_spec(
            {"spec": {"operations": [], "source": {"trace": {"format": "borg"}}}}
        )
    with pytest.raises(ScenarioSpecError, match="exactly one key"):
        operations_from_spec({"spec": {"source": {"bogus": {}}}})
    with pytest.raises(ScenarioSpecError, match="must be integers"):
        operations_from_spec(
            _trace_source_doc(name="x.jsonl", format="borg", nodes="many")
        )


def test_faults_spec_from_doc_canonicalizes():
    from ksim_tpu.scenario import faults_spec_from_doc

    assert faults_spec_from_doc({"spec": {}}) == ""
    spec = faults_spec_from_doc(
        {
            "spec": {
                "faults": {
                    "replay.dispatch": "call:2@device",
                    "jobs.run": "first:1",
                }
            }
        }
    )
    # Sorted, comma-joined -> exactly the KSIM_FAULTS grammar.
    assert spec == "jobs.run=first:1,replay.dispatch=call:2@device"
    from ksim_tpu.faults import FaultPlane

    plane = FaultPlane()
    plane.configure(spec)  # the canonical string parses as-is


def test_faults_spec_from_doc_refusals():
    from ksim_tpu.scenario import faults_spec_from_doc

    for bad in (
        {"spec": {"faults": ["replay.dispatch=always"]}},  # list, not mapping
        {"spec": {"faults": {"replay.dispatch": 3}}},
        {"spec": {"faults": {"": "always"}}},
    ):
        with pytest.raises(ScenarioSpecError, match="spec.faults"):
            faults_spec_from_doc(bad)
    with pytest.raises(ScenarioSpecError, match="malformed"):
        faults_spec_from_doc({"spec": {"faults": {"a=b": "always"}}})


def test_faults_spec_schedule_cannot_smuggle_sites():
    """A schedule value embedding ';'/',' would re-split inside
    FaultPlane.configure into EXTRA site=schedule entries, bypassing a
    caller's site allowlist — refused at the spec surface."""
    from ksim_tpu.scenario import faults_spec_from_doc

    for sched in ("always;service.schedule=always", "always,jobs.run=first:1"):
        with pytest.raises(ScenarioSpecError, match="one schedule per site"):
            faults_spec_from_doc({"spec": {"faults": {"replay.dispatch": sched}}})
