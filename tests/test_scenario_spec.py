"""KEP-140 Scenario document loading + patch/done replay semantics."""

import pytest

from ksim_tpu.scenario import (
    ScenarioRunner,
    ScenarioSpecError,
    load_scenario,
    operations_from_spec,
)
from ksim_tpu.scenario.spec import merge_patch
from tests.helpers import make_node, make_pod


def scenario_doc():
    return {
        "apiVersion": "simulation.sigs.x-k8s.io/v1alpha1",
        "kind": "Scenario",
        "metadata": {"name": "s1"},
        "spec": {
            "operations": [
                {
                    "id": "create-node",
                    "step": 0,
                    "createOperation": {"object": {"kind": "Node", **make_node("n1", cpu="8")}},
                },
                {
                    "id": "create-pod",
                    "step": 1,
                    "createOperation": {"object": {"kind": "Pod", **make_pod("p1", cpu="1")}},
                },
                {
                    "id": "label-node",
                    "step": 2,
                    "patchOperation": {
                        "typeMeta": {"kind": "Node"},
                        "objectMeta": {"name": "n1"},
                        "patch": '{"metadata": {"labels": {"zone": "a"}}}',
                    },
                },
                {"id": "finish", "step": 3, "doneOperation": {}},
                {
                    "id": "never-runs",
                    "step": 4,
                    "deleteOperation": {
                        "typeMeta": {"kind": "Node"},
                        "objectMeta": {"name": "n1"},
                    },
                },
            ]
        },
    }


def test_operations_from_spec_shapes():
    ops = operations_from_spec(scenario_doc())
    assert [o.op for o in ops] == ["create", "create", "patch", "done", "delete"]
    assert ops[0].kind == "nodes" and ops[1].kind == "pods"
    assert ops[2].name == "n1" and ops[2].obj == {"metadata": {"labels": {"zone": "a"}}}


def test_yaml_round_trip():
    import yaml

    ops = load_scenario(yaml.safe_dump(scenario_doc()))
    assert len(ops) == 5


def test_replay_applies_patch_and_stops_at_done():
    runner = ScenarioRunner()
    res = runner.run(operations_from_spec(scenario_doc()))
    assert res.succeeded
    # done at step 3 halts before the delete at step 4.
    assert [s.step for s in res.steps] == [0, 1, 2, 3]
    node = runner.store.get("nodes", "n1")
    assert node["metadata"]["labels"]["zone"] == "a"
    assert res.pods_scheduled == 1
    pod = runner.store.list("pods")[0]
    assert pod["spec"]["nodeName"] == "n1"


def test_invalid_operations_rejected():
    with pytest.raises(ScenarioSpecError):
        operations_from_spec({"spec": {"operations": [{"id": "x", "step": 0}]}})
    with pytest.raises(ScenarioSpecError):
        operations_from_spec(
            {"spec": {"operations": [
                {"step": 0, "createOperation": {"object": {"kind": "Pod"}},
                 "doneOperation": {}},
            ]}}
        )
    with pytest.raises(ScenarioSpecError):
        operations_from_spec(
            {"spec": {"operations": [
                {"step": 0, "createOperation": {"object": {"kind": "Gadget",
                                                           "metadata": {"name": "g"}}}},
            ]}}
        )
    with pytest.raises(ScenarioSpecError):
        operations_from_spec({})


def test_merge_patch_rfc7386():
    target = {"a": {"b": 1, "c": 2}, "d": [1, 2]}
    patch = {"a": {"b": None, "e": 3}, "d": [9]}
    assert merge_patch(target, patch) == {"a": {"c": 2, "e": 3}, "d": [9]}


def test_scheduler_simulation_document(tmp_path):
    """KEP-184 one-shot run: simulator spec + scenario file -> status +
    result file (keps/184-scheduler-simulation/README.md)."""
    import json
    import yaml

    from ksim_tpu.scenario.simulation import run_scheduler_simulation

    scenario_path = tmp_path / "scenario.yaml"
    scenario_path.write_text(yaml.safe_dump(scenario_doc()))
    result_path = tmp_path / "result.json"
    doc = {
        "kind": "SchedulerSimulation",
        "metadata": {"name": "sim1"},
        "spec": {
            "simulator": {
                "schedulerConfig": {"profiles": [{"plugins": {"multiPoint": {
                    "disabled": [{"name": "InterPodAffinity"}]}}}]},
                "recordMode": "full",
            },
            "scenarioTemplateFilePath": str(scenario_path),
            "scenarioResultFilePath": str(result_path),
        },
    }
    out = run_scheduler_simulation(doc)
    assert out["status"]["phase"] == "Succeeded"
    assert out["status"]["result"]["podsScheduled"] == 1
    stored = json.loads(result_path.read_text())
    assert stored["status"]["result"]["eventsApplied"] == 4


def test_scheduler_simulation_failure_phase():
    from ksim_tpu.scenario.simulation import run_scheduler_simulation

    out = run_scheduler_simulation({
        "spec": {
            "scenario": {"spec": {"operations": [
                {"step": 0, "deleteOperation": {
                    "typeMeta": {"kind": "Node"},
                    "objectMeta": {"name": "missing"}}},
            ]}},
        }
    })
    assert out["status"]["phase"] == "Failed"
    assert "NotFound" in out["status"]["message"]
