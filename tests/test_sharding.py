"""Sharded execution on the virtual 8-device CPU mesh: results must be
identical to single-device execution."""

import numpy as np

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.engine.sharding import make_mesh
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import random_cluster


def _engines(record="full"):
    nodes, pods = random_cluster(3, n_nodes=30, n_pods=50)
    feats = Featurizer().featurize(nodes, pods)
    mk = lambda: Engine(feats, default_plugins(feats), record=record)
    return mk(), mk()


def test_batch_eval_sharded_equals_single_device():
    single, sharded = _engines()
    mesh = make_mesh(8, dp=2)
    sharded.shard(mesh)
    a = single.evaluate_batch()
    b = sharded.evaluate_batch()
    np.testing.assert_array_equal(a.selected, b.selected)
    np.testing.assert_array_equal(a.reason_bits, b.reason_bits)
    np.testing.assert_array_equal(a.scores, b.scores)
    np.testing.assert_array_equal(a.total, b.total)


def test_schedule_sharded_equals_single_device():
    single, sharded = _engines(record="selection")
    mesh = make_mesh(8, dp=1)  # replicated pods, tp=8 over nodes
    sharded.shard(mesh)
    ra, sa = single.schedule()
    rb, sb = sharded.schedule()
    np.testing.assert_array_equal(ra.selected, rb.selected)
    np.testing.assert_array_equal(np.asarray(sa.requested), np.asarray(sb.requested))


def test_sharded_churn_replay_equals_single_device():
    """End-to-end churn replay (VERDICT r02 item 8): a scheduler service
    whose engines are laid out over the 8-device mesh must produce the
    SAME bindings as the single-device service, step by step, with
    carries (capacity/topology commits) flowing through the sharded scan."""
    from ksim_tpu.scenario import ScenarioRunner, churn_scenario
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore

    def run(mesh):
        store = ClusterStore()
        svc = SchedulerService(
            store,
            record="selection",
            preemption=False,
            max_pods_per_pass=64,
            shard_mesh=mesh,
        )
        runner = ScenarioRunner(store, svc)
        res = runner.run(
            churn_scenario(7, n_nodes=24, n_events=400, ops_per_step=40)
        )
        bindings = {
            f"{p['metadata']['namespace']}/{p['metadata']['name']}": p["spec"].get("nodeName")
            for p in store.list("pods")
        }
        return res, bindings

    res_single, bind_single = run(None)
    res_sharded, bind_sharded = run(make_mesh(8, dp=1))
    assert res_single.pods_scheduled == res_sharded.pods_scheduled
    assert res_single.unschedulable_attempts == res_sharded.unschedulable_attempts
    assert [s.scheduled for s in res_single.steps] == [
        s.scheduled for s in res_sharded.steps
    ]
    assert bind_single == bind_sharded
