"""Sharded execution on the virtual 8-device CPU mesh: results must be
identical to single-device execution."""

import numpy as np

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.engine.sharding import make_mesh
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import random_cluster


def _engines(record="full"):
    nodes, pods = random_cluster(3, n_nodes=30, n_pods=50)
    feats = Featurizer().featurize(nodes, pods)
    mk = lambda: Engine(feats, default_plugins(feats), record=record)
    return mk(), mk()


def test_batch_eval_sharded_equals_single_device():
    single, sharded = _engines()
    mesh = make_mesh(8, dp=2)
    sharded.shard(mesh)
    a = single.evaluate_batch()
    b = sharded.evaluate_batch()
    np.testing.assert_array_equal(a.selected, b.selected)
    np.testing.assert_array_equal(a.reason_bits, b.reason_bits)
    np.testing.assert_array_equal(a.scores, b.scores)
    np.testing.assert_array_equal(a.total, b.total)


def test_schedule_sharded_equals_single_device():
    single, sharded = _engines(record="selection")
    mesh = make_mesh(8, dp=1)  # replicated pods, tp=8 over nodes
    sharded.shard(mesh)
    ra, sa = single.schedule()
    rb, sb = sharded.schedule()
    np.testing.assert_array_equal(ra.selected, rb.selected)
    np.testing.assert_array_equal(np.asarray(sa.requested), np.asarray(sb.requested))
