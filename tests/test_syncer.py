"""Resource syncer + one-shot importer (reference simulator/syncer/,
simulator/oneshotimporter/): mirroring semantics, mandatory mutators and
filters, NotFound tolerance — tested with two in-memory stores, the way
the reference fakes two clusters with fake dynamic clients
(syncer_test.go:18-25)."""

from __future__ import annotations

import time

from ksim_tpu.oneshotimporter import OneShotImporter
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.snapshot import SnapshotService
from ksim_tpu.syncer import Syncer, SyncerOptions
from tests.helpers import make_node, make_pod


def _wait(pred, timeout=5.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_initial_sync_strips_metadata_and_mutates_pods():
    src, dst = ClusterStore(), ClusterStore()
    src.create("nodes", make_node("n0"))
    pod = make_pod("p0")
    pod["spec"]["serviceAccountName"] = "robot"
    pod["metadata"]["ownerReferences"] = [{"kind": "ReplicaSet", "name": "rs"}]
    pod["metadata"]["uid"] = "src-uid-42"
    src.create("pods", pod)
    Syncer(src, dst).sync_once()
    got = dst.get("pods", "p0")
    assert "serviceAccountName" not in got["spec"]
    assert "ownerReferences" not in got["metadata"]
    # Destination assigns its own uid (the source's is stripped).
    assert got["metadata"]["uid"] != "src-uid-42"
    assert dst.get("nodes", "n0")


def test_watch_mirroring_and_scheduled_pod_filter():
    src, dst = ClusterStore(), ClusterStore()
    syncer = Syncer(src, dst).run()
    try:
        src.create("nodes", make_node("n0"))
        assert _wait(lambda: dst.list("nodes"))
        # Unscheduled pod update mirrors; scheduled pod update does not.
        src.create("pods", make_pod("p0"))
        assert _wait(lambda: dst.list("pods"))
        src.patch("pods", "p0", "default",
                  lambda o: o["metadata"]["labels"].__setitem__("x", "1"))
        assert _wait(lambda: dst.get("pods", "p0")["metadata"]["labels"].get("x") == "1")
        # Bind on the SOURCE: the update must be filtered out.
        src.patch("pods", "p0", "default",
                  lambda o: o["spec"].__setitem__("nodeName", "n0"))
        time.sleep(0.3)
        assert "nodeName" not in dst.get("pods", "p0")["spec"]
        # Deletes mirror; deleting an already-missing object is tolerated.
        src.delete("pods", "p0")
        assert _wait(lambda: not dst.list("pods"))
        dst.create("nodes", make_node("only-dst"))
        src.create("nodes", make_node("only-dst"))
        src.delete("nodes", "only-dst")
        assert _wait(lambda: "only-dst" not in
                     [n["metadata"]["name"] for n in dst.list("nodes")])
    finally:
        syncer.stop()


def test_pv_claimref_uid_reresolved_against_destination():
    src, dst = ClusterStore(), ClusterStore()
    pvc = {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "claim", "namespace": "default"}, "spec": {},
    }
    src.create("persistentvolumeclaims", dict(pvc))
    pv = {
        "apiVersion": "v1", "kind": "PersistentVolume",
        "metadata": {"name": "vol"},
        "spec": {"claimRef": {"name": "claim", "namespace": "default",
                              "uid": "stale-src-uid"}},
        "status": {"phase": "Bound"},
    }
    src.create("persistentvolumes", pv)
    Syncer(src, dst).sync_once()
    got = dst.get("persistentvolumes", "vol")
    dst_pvc_uid = dst.get("persistentvolumeclaims", "claim")["metadata"]["uid"]
    assert got["spec"]["claimRef"]["uid"] == dst_pvc_uid != "stale-src-uid"


def test_user_filters_and_mutators_compose():
    src, dst = ClusterStore(), ClusterStore()
    src.create("nodes", make_node("keep"))
    src.create("nodes", make_node("drop"))
    opts = SyncerOptions(
        additional_filtering={
            "nodes": lambda o, d, e: o["metadata"]["name"] != "drop"
        },
        additional_mutating={
            "nodes": lambda o, d, e: {
                **o, "metadata": {**o["metadata"],
                                  "labels": {**o["metadata"].get("labels", {}),
                                             "synced": "true"}},
            }
        },
    )
    Syncer(src, dst, opts).sync_once()
    names = [n["metadata"]["name"] for n in dst.list("nodes")]
    assert names == ["keep"]
    assert dst.get("nodes", "keep")["metadata"]["labels"]["synced"] == "true"


def test_oneshot_importer_ignores_scheduler_config_and_errors():
    src, dst = ClusterStore(), ClusterStore()

    class FakeSched:
        def __init__(self):
            self.applied = None

        def get_scheduler_config(self):
            return {"profiles": [{"schedulerName": "src-sched"}]}

        def apply_scheduler_config(self, cfg):
            self.applied = cfg

    src_svc = SnapshotService(src, scheduler_service=FakeSched())
    dst_sched = FakeSched()
    dst_svc = SnapshotService(dst, scheduler_service=dst_sched)
    src.create("nodes", make_node("n0"))
    src.create("pods", make_pod("p0"))
    OneShotImporter(dst_svc, src_svc).import_cluster_resources()
    assert dst.get("nodes", "n0") and dst.get("pods", "p0")
    # The source's scheduler config is never applied (importer.go note).
    assert dst_sched.applied is None
