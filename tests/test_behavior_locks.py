"""The repo's churn behavior locks, asserted in-suite (round-5 verdict #1).

The flagship replay's counts (seed 0, 2000 nodes — repo CLAUDE.md) were
previously enforced only by bench discipline: a parity regression (the
class the locks exist to catch) would fail only if someone re-ran the
bench and eyeballed the counts.  BENCH_r04.json proved the gap — its TPU
churn recorded 52582/42840 against the 52781/42829 lock and nothing
noticed, because the f32 fast mode diverged ACROSS PLATFORMS (TPU's
approximate f32 divide truncated exact integer ratios one ulp low in
InterPodAffinity's normalize, and backend f32 log ulps flipped
PodTopologySpread's round()).  Both kernels are now platform-
deterministic by construction (integer normalize floor; trace-time log
table + fixed-order reduce), so ONE set of counts is the contract on
every backend, in both modes — these tests pin the 6k prefix (~15 s,
the 50k run is bench-tier) exactly as the bench runs it
(ScenarioRunner(max_pods_per_pass=1024, pod_bucket_min=128),
ops_per_step=100; bench.py child_churn).

Reference intent: replay parity is the product metric — recorded
results as ground truth (storereflector.go:78-146).
"""

import jax
import pytest

from ksim_tpu.scenario import ScenarioRunner, churn_scenario

# seed 0, 2000 nodes, 6000 events -> applied events include the step
# padding the generator emits (6430), and the scheduling outcomes are
# the locked prefix of the 50k flagship replay (50k locks: 52781/42829).
LOCK_SCHEDULED = 2524
LOCK_UNSCHEDULABLE = 471
LOCK_EVENTS = 6430


def _run_locked_churn() -> tuple[int, int, int]:
    runner = ScenarioRunner(max_pods_per_pass=1024, pod_bucket_min=128)
    res = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
    )
    return res.pods_scheduled, res.unschedulable_attempts, res.events_applied


@pytest.mark.parametrize("x64", [False, True], ids=["f32-fast", "exact-x64"])
def test_churn_lock_6k_seed0(x64):
    """Both modes land on identical counts (exact mode has always been
    platform-identical; f32 now is too — drift here means a scoring-path
    behavior change that MUST be deliberate and re-baselined, see
    docs/churn_floor.md)."""
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", x64)
    try:
        scheduled, unschedulable, events = _run_locked_churn()
    finally:
        jax.config.update("jax_enable_x64", prev)
    assert events == LOCK_EVENTS
    assert (scheduled, unschedulable) == (LOCK_SCHEDULED, LOCK_UNSCHEDULABLE)


def test_churn_lock_6k_holds_with_tracing_enabled(tmp_path):
    """Observability must be zero-perturbation: the locked prefix's
    counts are byte-identical with the trace plane FULLY enabled
    (``KSIM_TRACE_OUT`` set: histograms + event ring + file export),
    and the emitted Chrome-trace JSON validates with the per-pass
    phase spans on it."""
    import json
    import os

    from ksim_tpu.obs import TRACE

    out = tmp_path / "trace.json"
    prev_state = (TRACE._active, TRACE._ring_on, TRACE._user_disabled)
    prev_x64 = jax.config.jax_enable_x64
    os.environ["KSIM_TRACE_OUT"] = str(out)
    try:
        TRACE.configure_from_env()
        jax.config.update("jax_enable_x64", False)
        scheduled, unschedulable, events = _run_locked_churn()
        assert events == LOCK_EVENTS
        assert (scheduled, unschedulable) == (LOCK_SCHEDULED, LOCK_UNSCHEDULABLE)
        TRACE.export_chrome(str(out))
        doc = json.loads(out.read_text())
        names = {e["name"] for e in doc["traceEvents"]}
        # The per-pass path's phase spans + pass-outcome events.
        assert {"runner.step", "service.schedule", "service.pass"} <= names
        n_sched_spans = sum(
            1
            for e in doc["traceEvents"]
            if e["name"] == "service.schedule" and e.get("ph") == "X"
        )
        assert n_sched_spans >= 1
    finally:
        jax.config.update("jax_enable_x64", prev_x64)
        os.environ.pop("KSIM_TRACE_OUT", None)
        TRACE.out_path = None
        # Drop the 6k run's ring contents (up to 65536 record dicts)
        # and restore the exact pre-test flags — NOT via disable(),
        # whose sticky opt-out would leave ensure_timing inert for
        # every later test in the process.
        TRACE.reset()
        TRACE._active, TRACE._ring_on, TRACE._user_disabled = prev_state


@pytest.mark.slow
def test_churn_lock_6k_holds_under_dispatch_faults_with_recovery(monkeypatch):
    """The chaos leg (`make lock-check`, round 15): the locked 6k counts
    are BYTE-IDENTICAL while the fault plane kills the first two device
    dispatches, the breaker trips, and half-open recovery (a cooldown'd
    probe segment) re-promotes the device path mid-run.  Faults change
    WHERE steps execute (host vs device), never WHAT they compute —
    the durability round's end-to-end breaker-recovery proof."""
    from ksim_tpu.faults import FAULTS

    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "2")
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_COOLDOWN_S", "0.05")
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    FAULTS.reset()
    FAULTS.arm("replay.dispatch", "first:2@device")
    try:
        runner = ScenarioRunner(
            max_pods_per_pass=1024, pod_bucket_min=128, device_replay=True
        )
        res = runner.run(
            churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
        )
    finally:
        FAULTS.reset()
        jax.config.update("jax_enable_x64", prev_x64)
    assert res.events_applied == LOCK_EVENTS
    assert (res.pods_scheduled, res.unschedulable_attempts) == (
        LOCK_SCHEDULED,
        LOCK_UNSCHEDULABLE,
    )
    d = runner.replay_driver
    assert d.breaker_closes >= 1, d.stats()["breaker"]  # recovered mid-run
    assert d.breaker_tripped is False
    assert d.device_steps > 0
    assert d.device_steps + d.fallback_steps == len(res.steps)


# The trace workload family (round 14, ksim_tpu/traces): the bundled
# hand-checked Borg fixture compiled at 24 nodes / ops_per_step=2 —
# the SECOND locked-count family next to synthetic churn, and the
# first priority-DIVERSE one (trace tiers land on PRIORITY_LADDER, so
# windows are not priority-flat).  bench.py's churn_trace rung replays
# the same compilation.
TRACE_LOCK_SCHEDULED = 56
TRACE_LOCK_UNSCHEDULABLE = 19
TRACE_LOCK_EVENTS = 126


def test_trace_lock_borg_mini_device_vs_per_pass():
    """The trace-ingestion acceptance lock: the bundled fixture compiles
    deterministically and replays byte-identically through the per-pass
    AND the device-resident path, with the device path carrying EVERY
    step (0 fallbacks — in-vocabulary by construction, and create-free
    steps with eligible pods stay on-device since the round-14
    featurize-prediction refinement for static node universes)."""
    from ksim_tpu.traces import trace_operations

    jax.config.update("jax_enable_x64", False)
    ops = trace_operations(
        "tests/fixtures/traces/borg_mini.jsonl",
        "borg",
        nodes=24,
        ops_per_step=2,
    )
    base_r = ScenarioRunner(pod_bucket_min=64)
    base = base_r.run(list(ops))
    assert base.events_applied == TRACE_LOCK_EVENTS
    assert (base.pods_scheduled, base.unschedulable_attempts) == (
        TRACE_LOCK_SCHEDULED,
        TRACE_LOCK_UNSCHEDULABLE,
    )
    dev_r = ScenarioRunner(pod_bucket_min=64, device_replay=True)
    dev = dev_r.run(list(ops))
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        TRACE_LOCK_SCHEDULED,
        TRACE_LOCK_UNSCHEDULABLE,
    )
    base_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in base.steps
    ]
    dev_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in dev.steps
    ]
    assert dev_sig == base_sig
    driver = dev_r.replay_driver
    assert driver.fallback_steps == 0, driver.unsupported
    assert driver.device_steps == len(dev.steps)


def test_trace_lock_borg_mini_holds_with_streaming_ingest():
    """Round 20: the SAME locked counts through the windowed streaming
    pipeline (traces/stream.py feeding the runner window-by-window,
    tiny windows so every step crosses a boundary) on BOTH the per-pass
    and the device path — streaming is a transport change, not a
    behavior change."""
    from ksim_tpu.traces import stream_trace_operations

    jax.config.update("jax_enable_x64", False)

    def fresh():
        return stream_trace_operations(
            "tests/fixtures/traces/borg_mini.jsonl",
            "borg",
            nodes=24,
            ops_per_step=2,
            window=8,
            queue_windows=2,
        )

    base = ScenarioRunner(pod_bucket_min=64).run(fresh())
    assert base.events_applied == TRACE_LOCK_EVENTS
    assert (base.pods_scheduled, base.unschedulable_attempts) == (
        TRACE_LOCK_SCHEDULED,
        TRACE_LOCK_UNSCHEDULABLE,
    )
    dev_r = ScenarioRunner(pod_bucket_min=64, device_replay=True)
    dev = dev_r.run(fresh())
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        TRACE_LOCK_SCHEDULED,
        TRACE_LOCK_UNSCHEDULABLE,
    )
    assert [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in dev.steps
    ] == [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in base.steps
    ]
    assert dev_r.replay_driver.fallback_steps == 0


# The full 50k flagship locks (repo CLAUDE.md).
LOCK_50K_SCHEDULED = 52_781
LOCK_50K_UNSCHEDULABLE = 42_829


@pytest.mark.slow
def test_churn_lock_50k_stepwise_device_vs_per_pass():
    """The one-command behavior-lock verification (`make lock-check`):
    replay the FULL 50k stream through the per-pass path AND the
    device-resident path (preemption enabled — a no-op on this stream,
    which is exactly what the lock asserts) and require the 52781/42829
    totals plus stepwise-identical (scheduled, unschedulable, pending)
    triples between the two paths.  ~10 min CPU; bench-tier before this
    test existed."""
    jax.config.update("jax_enable_x64", False)

    def run(device: bool, preemption: bool):
        runner = ScenarioRunner(
            max_pods_per_pass=1024,
            pod_bucket_min=128,
            preemption=preemption,
            device_replay=device,
        )
        res = runner.run(
            churn_scenario(0, n_nodes=2000, n_events=50_000, ops_per_step=100)
        )
        return runner, res

    _base_r, base = run(device=False, preemption=False)
    assert (base.pods_scheduled, base.unschedulable_attempts) == (
        LOCK_50K_SCHEDULED,
        LOCK_50K_UNSCHEDULABLE,
    )
    dev_r, dev = run(device=True, preemption=True)
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        LOCK_50K_SCHEDULED,
        LOCK_50K_UNSCHEDULABLE,
    )
    base_sig = [(s.step, s.scheduled, s.unschedulable, s.pending_after) for s in base.steps]
    dev_sig = [(s.step, s.scheduled, s.unschedulable, s.pending_after) for s in dev.steps]
    assert dev_sig == base_sig
    driver = dev_r.replay_driver
    # Preemption/tail support must keep the stream on-device: PR 1's
    # baseline with preemption enabled was 0 device steps (the whole
    # stream rejected), and even without it the tail step fell back.
    assert driver.fallback_steps == 0, driver.unsupported
    assert driver.device_steps == len(dev.steps)
    # Incremental lowering (round 10), asserted with the cache and the
    # double-buffered prelower fully ON (they are the defaults the
    # counts above were just produced under):
    cache = driver.stats()["lower_cache"]
    # (a) a clean stream keeps the lowered-universe cache hot — every
    # segment after the first is a hit and nothing ever flushed it;
    assert cache["misses"] == 1 and cache["invalidations"] == 0, cache
    assert cache["hits"] == driver.device_round_trips - 1
    # every non-final window's speculative prefix was consumed;
    assert driver.prelower_discarded == 0
    assert driver.prelower_consumed == driver.prelower_windows
    # (b) the counter-based O(delta) guard: every steady-state (cache
    # hit) segment built fresh featurize rows proportional to ITS
    # window's events — never to the universe size.  Counters, not
    # timings, so the guard is stable in CI.
    steady = [e for e in driver.lower_log if e["cache_hit"]]
    assert steady, driver.lower_log
    for entry in steady:
        assert entry["rows_built"] <= entry["events"] + 32, entry


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["dedupe", "vmap"])
def test_churn_fleet_lock_6k_lanes8(mode, monkeypatch):
    """The fleet parity lock (`make lock-check`, round 12): 8 lanes of
    the locked 6k prefix through BOTH cohort dispatch modes — every
    lane must land 2524/471 with stepwise triples identical to the solo
    device run, the whole fleet on-device, and the shared universe
    lowered ONCE per window (counter-based guard: only the cohort
    leader's driver ever lowers; every follower records zero).

    The ``vmap`` leg runs the genuinely lane-stacked
    ``_fleet_segment_fn`` program (KSIM_FLEET_VMAP=1) — the proof that
    the carry, the RNG-free kernels and the reconcile boundaries are
    lane-INDEPENDENT, not merely that one trajectory fans out.  The
    ``dedupe`` leg locks the production default (one dispatch, S
    decodes/reconciles, each lane's verify_segment proving its own
    store)."""
    jax.config.update("jax_enable_x64", False)
    if mode == "vmap":
        monkeypatch.setenv("KSIM_FLEET_VMAP", "1")
    else:
        monkeypatch.delenv("KSIM_FLEET_VMAP", raising=False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, preemption=True)

    def stream():
        return churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)

    solo_r = ScenarioRunner(device_replay=True, **kw)
    solo = solo_r.run(stream())
    assert (solo.pods_scheduled, solo.unschedulable_attempts) == (
        LOCK_SCHEDULED,
        LOCK_UNSCHEDULABLE,
    )
    solo_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in solo.steps
    ]
    fleet_r = ScenarioRunner(device_replay=True, fleet=8, **kw)
    agg = fleet_r.run(stream())
    assert agg.pods_scheduled == 8 * LOCK_SCHEDULED
    assert agg.unschedulable_attempts == 8 * LOCK_UNSCHEDULABLE
    for ln in fleet_r.fleet_lanes:
        r = ln.result
        assert (r.pods_scheduled, r.unschedulable_attempts) == (
            LOCK_SCHEDULED,
            LOCK_UNSCHEDULABLE,
        ), f"lane {ln.idx}"
        sig = [
            (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in r.steps
        ]
        assert sig == solo_sig, f"lane {ln.idx} stepwise divergence"
        assert ln.convergent
        assert ln.driver.fallback_steps == 0, ln.driver.unsupported
    stats = fleet_r.fleet_driver.stats()
    assert stats["cohort_mode"] == mode
    # The lowered-once-per-window guard: one driver (the cohort leader)
    # did ALL the lowering; 7 followers did none — and the leader's
    # lowered-universe cache stayed hot exactly as the solo run's does.
    lowerings = stats["lane_lowerings"]
    assert sum(lowerings) == max(lowerings) > 0, stats
    assert lowerings.count(0) == 7, stats
    assert stats["lanes_on_device"] == 1.0, stats
    assert stats["group_dispatches"] == stats["shared_lowerings"]
    leader = max(
        (ln.driver for ln in fleet_r.fleet_lanes), key=lambda d: len(d.lower_log)
    )
    cache = leader.stats()["lower_cache"]
    assert cache["misses"] == 1 and cache["invalidations"] == 0, cache


# ---------------------------------------------------------------------------
# Round 17: the locked counts through the tp-SHARDED device path
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_churn_lock_6k_sharded_tp8(monkeypatch):
    """The flagship locked prefix with the node axis laid over a tp=8
    mesh (8 virtual CPU devices, conftest): 2524/471 byte-identical,
    stepwise-identical to the SOLO device run, same device coverage,
    zero shard_mesh fallbacks, every lowered segment at tp=8.  GSPMD
    value-preservation is the claim under test — the collectives the
    partitioner inserts must never show up in the counts."""
    jax.config.update("jax_enable_x64", False)

    def run():
        runner = ScenarioRunner(
            max_pods_per_pass=1024,
            pod_bucket_min=128,
            device_replay=True,
            device_segment_steps=16,
        )
        res = runner.run(
            churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
        )
        return runner, res

    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    solo_r, solo = run()
    monkeypatch.setenv("KSIM_REPLAY_TP", "8")
    shard_r, shard = run()
    assert shard.events_applied == LOCK_EVENTS
    assert (shard.pods_scheduled, shard.unschedulable_attempts) == (
        LOCK_SCHEDULED,
        LOCK_UNSCHEDULABLE,
    )
    solo_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in solo.steps
    ]
    shard_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in shard.steps
    ]
    assert shard_sig == solo_sig
    d = shard_r.replay_driver
    assert d.device_steps == solo_r.replay_driver.device_steps
    assert d.device_steps >= 32
    assert "shard_mesh" not in d.unsupported, d.unsupported
    assert sorted({e["tp"] for e in d.lower_log}) == [8], d.lower_log
    # The per-shard full-record budget evidence rides on every entry.
    assert all("full_bytes_per_shard" in e for e in d.lower_log)


# ---------------------------------------------------------------------------
# Round 19: the locked counts through the 2-D (tp x dp) fleet mesh
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_churn_fleet_lock_6k_tp4_dp2(monkeypatch):
    """The flagship locked prefix through the round-19 2-D fleet mesh
    (`make mesh-check`): 2 lanes laid over dp composed with tp=4 node
    sharding on the 8 virtual devices — every lane 2524/471 stepwise-
    identical to the SOLO unsharded device run, the whole fleet
    on-device, the (2, 4) grid built, and every fleet segment lowered
    at the declared width.  This is the composition claim: GSPMD
    value-preservation (round 17) and lane-independence (round 12)
    hold SIMULTANEOUSLY, with the cond-gated preemption search in the
    lowered program.  (Mesh dispatches run the NON-donating twin —
    donated multi-device carries race on the virtual-device CPU
    backend; see replay.py's _DONATE_ARGNUMS note.)"""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, preemption=True)

    def stream():
        return churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)

    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    monkeypatch.delenv("KSIM_FLEET_DP", raising=False)
    solo_r = ScenarioRunner(device_replay=True, **kw)
    solo = solo_r.run(stream())
    assert (solo.pods_scheduled, solo.unschedulable_attempts) == (
        LOCK_SCHEDULED,
        LOCK_UNSCHEDULABLE,
    )
    solo_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in solo.steps
    ]
    monkeypatch.setenv("KSIM_FLEET_DP", "2")
    monkeypatch.setenv("KSIM_REPLAY_TP", "4")
    fleet_r = ScenarioRunner(device_replay=True, fleet=2, **kw)
    agg = fleet_r.run(stream())
    assert agg.pods_scheduled == 2 * LOCK_SCHEDULED
    assert agg.unschedulable_attempts == 2 * LOCK_UNSCHEDULABLE
    for ln in fleet_r.fleet_lanes:
        r = ln.result
        assert (r.pods_scheduled, r.unschedulable_attempts) == (
            LOCK_SCHEDULED,
            LOCK_UNSCHEDULABLE,
        ), f"lane {ln.idx}"
        sig = [
            (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in r.steps
        ]
        assert sig == solo_sig, f"lane {ln.idx} stepwise divergence"
        assert ln.convergent
        assert ln.driver.fallback_steps == 0, ln.driver.unsupported
    fd = fleet_r.fleet_driver
    stats = fd.stats()
    assert stats["cohort_mode"] == "vmap"
    assert stats["lanes_on_device"] == 1.0, stats
    with fd._mesh_lock:
        assert not fd._mesh_failed
        assert (2, 4) in fd._mesh, fd._mesh
    tps = sorted({e["tp"] for ln in fleet_r.fleet_lanes for e in ln.driver.lower_log})
    assert tps == [4], tps


@pytest.mark.slow
def test_churn_lock_50k_stepwise_sharded_tp8(monkeypatch):
    """The FULL 50k flagship stream under the tp=8 mesh: 52781/42829,
    stepwise-identical to the per-pass path, zero fallbacks — the
    100k-node-scale memory story (per-shard budgets) must not cost a
    single count.  Bench-tier wall clock; `make lock-check`."""
    jax.config.update("jax_enable_x64", False)

    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    base = ScenarioRunner(max_pods_per_pass=1024, pod_bucket_min=128).run(
        churn_scenario(0, n_nodes=2000, n_events=50_000, ops_per_step=100)
    )
    assert (base.pods_scheduled, base.unschedulable_attempts) == (
        LOCK_50K_SCHEDULED,
        LOCK_50K_UNSCHEDULABLE,
    )
    monkeypatch.setenv("KSIM_REPLAY_TP", "8")
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        device_segment_steps=16,
    )
    dev = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=50_000, ops_per_step=100)
    )
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        LOCK_50K_SCHEDULED,
        LOCK_50K_UNSCHEDULABLE,
    )
    base_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in base.steps
    ]
    dev_sig = [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in dev.steps
    ]
    assert dev_sig == base_sig
    d = runner.replay_driver
    assert d.fallback_steps == 0, d.unsupported
    assert d.device_steps == len(dev.steps)
    assert sorted({e["tp"] for e in d.lower_log}) == [8]
