"""The repo's churn behavior locks, asserted in-suite (round-5 verdict #1).

The flagship replay's counts (seed 0, 2000 nodes — repo CLAUDE.md) were
previously enforced only by bench discipline: a parity regression (the
class the locks exist to catch) would fail only if someone re-ran the
bench and eyeballed the counts.  BENCH_r04.json proved the gap — its TPU
churn recorded 52582/42840 against the 52781/42829 lock and nothing
noticed, because the f32 fast mode diverged ACROSS PLATFORMS (TPU's
approximate f32 divide truncated exact integer ratios one ulp low in
InterPodAffinity's normalize, and backend f32 log ulps flipped
PodTopologySpread's round()).  Both kernels are now platform-
deterministic by construction (integer normalize floor; trace-time log
table + fixed-order reduce), so ONE set of counts is the contract on
every backend, in both modes — these tests pin the 6k prefix (~15 s,
the 50k run is bench-tier) exactly as the bench runs it
(ScenarioRunner(max_pods_per_pass=1024, pod_bucket_min=128),
ops_per_step=100; bench.py child_churn).

Reference intent: replay parity is the product metric — recorded
results as ground truth (storereflector.go:78-146).
"""

import jax
import pytest

from ksim_tpu.scenario import ScenarioRunner, churn_scenario

# seed 0, 2000 nodes, 6000 events -> applied events include the step
# padding the generator emits (6430), and the scheduling outcomes are
# the locked prefix of the 50k flagship replay (50k locks: 52781/42829).
LOCK_SCHEDULED = 2524
LOCK_UNSCHEDULABLE = 471
LOCK_EVENTS = 6430


def _run_locked_churn() -> tuple[int, int, int]:
    runner = ScenarioRunner(max_pods_per_pass=1024, pod_bucket_min=128)
    res = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
    )
    return res.pods_scheduled, res.unschedulable_attempts, res.events_applied


@pytest.mark.parametrize("x64", [False, True], ids=["f32-fast", "exact-x64"])
def test_churn_lock_6k_seed0(x64):
    """Both modes land on identical counts (exact mode has always been
    platform-identical; f32 now is too — drift here means a scoring-path
    behavior change that MUST be deliberate and re-baselined, see
    docs/churn_floor.md)."""
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", x64)
    try:
        scheduled, unschedulable, events = _run_locked_churn()
    finally:
        jax.config.update("jax_enable_x64", prev)
    assert events == LOCK_EVENTS
    assert (scheduled, unschedulable) == (LOCK_SCHEDULED, LOCK_UNSCHEDULABLE)
