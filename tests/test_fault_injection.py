"""Fault injection: the watch-driven service must survive transient
store/engine failures.

The reference has NO fault injection anywhere (SURVEY.md §5); its
recovery story is retries + rollback.  These tests actively break the
store under the running service and assert the loop recovers — the
"add what the reference lacks" test tier.
"""

from __future__ import annotations

import time

from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.errors import SimulatorError
from tests.helpers import make_node, make_pod


class FlakyStore(ClusterStore):
    """Fails the first N rewrap (bind) calls, then behaves."""

    def __init__(self, fail_first: int) -> None:
        super().__init__()
        self.failures_left = fail_first
        self.failed = 0

    def rewrap(self, kind, name, namespace, build):
        if kind == "pods" and self.failures_left > 0:
            self.failures_left -= 1
            self.failed += 1
            raise SimulatorError("injected bind failure")
        return super().rewrap(kind, name, namespace, build)


def test_watch_loop_survives_bind_failures():
    """Injected bind failures abort a pass; the loop stays alive and the
    pod binds on a later pass once the fault clears."""
    store = FlakyStore(fail_first=2)
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1"))
    svc = SchedulerService(store)
    svc.start()
    try:
        deadline = time.time() + 120
        bound = None
        while time.time() < deadline and not bound:
            bound = store.get("pods", "p1", "default")["spec"].get("nodeName")
            time.sleep(0.1)
        assert store.failed >= 1, "fault was never exercised"
        assert bound == "n1", "service never recovered from injected bind failures"
        # The loop is still serving: a second pod schedules normally.
        store.create("pods", make_pod("p2"))
        deadline = time.time() + 120
        bound2 = None
        while time.time() < deadline and not bound2:
            bound2 = store.get("pods", "p2", "default")["spec"].get("nodeName")
            time.sleep(0.1)
        assert bound2 == "n1"
    finally:
        svc.stop()


def test_schedule_pending_propagates_but_leaves_store_consistent():
    """A hard mid-pass failure must not half-bind: the failing pod's
    write never happened, earlier pods' binds stand, and a plain retry
    completes the rest."""
    store = FlakyStore(fail_first=1)
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1", cpu="100m"))
    store.create("pods", make_pod("p2", cpu="100m"))
    svc = SchedulerService(store)
    try:
        svc.schedule_pending()
    except SimulatorError:
        pass
    states = {
        name: store.get("pods", name, "default")["spec"].get("nodeName")
        for name in ("p1", "p2")
    }
    # Exactly the failed write is missing; nothing is half-applied.
    assert store.failed == 1
    assert list(states.values()).count(None) >= 1
    # Retry completes the remainder.
    svc.schedule_pending()
    for name in ("p1", "p2"):
        assert store.get("pods", name, "default")["spec"].get("nodeName") == "n1"
