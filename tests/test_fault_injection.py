"""Fault injection: the watch-driven service must survive transient
store/engine failures.

The reference has NO fault injection anywhere (SURVEY.md §5); its
recovery story is retries + rollback.  These tests actively break the
store under the running service and assert the loop recovers — the
"add what the reference lacks" test tier.
"""

from __future__ import annotations

import time

from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.errors import SimulatorError
from tests.helpers import make_node, make_pod


class FlakyStore(ClusterStore):
    """Fails the first N rewrap (bind) calls, then behaves."""

    def __init__(self, fail_first: int) -> None:
        super().__init__()
        self.failures_left = fail_first
        self.failed = 0

    def rewrap(self, kind, name, namespace, build):
        if kind == "pods" and self.failures_left > 0:
            self.failures_left -= 1
            self.failed += 1
            raise SimulatorError("injected bind failure")
        return super().rewrap(kind, name, namespace, build)


def test_watch_loop_survives_bind_failures():
    """Injected bind failures abort a pass; the loop stays alive and the
    pod binds on a later pass once the fault clears."""
    store = FlakyStore(fail_first=2)
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1"))
    svc = SchedulerService(store)
    svc.start()
    try:
        deadline = time.time() + 120
        bound = None
        while time.time() < deadline and not bound:
            bound = store.get("pods", "p1", "default")["spec"].get("nodeName")
            time.sleep(0.1)
        assert store.failed >= 1, "fault was never exercised"
        assert bound == "n1", "service never recovered from injected bind failures"
        # The loop is still serving: a second pod schedules normally.
        store.create("pods", make_pod("p2"))
        deadline = time.time() + 120
        bound2 = None
        while time.time() < deadline and not bound2:
            bound2 = store.get("pods", "p2", "default")["spec"].get("nodeName")
            time.sleep(0.1)
        assert bound2 == "n1"
    finally:
        svc.stop()


def test_schedule_pending_propagates_but_leaves_store_consistent():
    """A hard mid-pass failure must not half-bind: the failing pod's
    write never happened, earlier pods' binds stand, and a plain retry
    completes the rest."""
    store = FlakyStore(fail_first=1)
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1", cpu="100m"))
    store.create("pods", make_pod("p2", cpu="100m"))
    svc = SchedulerService(store)
    try:
        svc.schedule_pending()
    except SimulatorError:
        pass
    states = {
        name: store.get("pods", name, "default")["spec"].get("nodeName")
        for name in ("p1", "p2")
    }
    # Exactly the failed write is missing; nothing is half-applied.
    assert store.failed == 1
    assert list(states.values()).count(None) >= 1
    # Retry completes the remainder.
    svc.schedule_pending()
    for name in ("p1", "p2"):
        assert store.get("pods", name, "default")["spec"].get("nodeName") == "n1"


# ---------------------------------------------------------------------------
# Round 8: fault-plane sites outside the replay executor
# ---------------------------------------------------------------------------


def test_service_schedule_fault_site_loop_survives():
    """An injected scheduling-pass fault aborts the pass before any
    bookkeeping mutates; the watch loop's containment retries and the
    pod still binds once the fault clears."""
    from ksim_tpu.faults import FAULTS

    FAULTS.reset()
    FAULTS.arm("service.schedule", "first:2")
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1"))
    svc = SchedulerService(store)
    svc.start()
    try:
        deadline = time.time() + 120
        bound = None
        while time.time() < deadline and not bound:
            bound = store.get("pods", "p1", "default")["spec"].get("nodeName")
            time.sleep(0.1)
        assert FAULTS.fired("service.schedule") >= 1, "fault never exercised"
        assert bound == "n1"
    finally:
        svc.stop()
        FAULTS.reset()


def test_writeback_push_fault_site_retries_and_lands(monkeypatch):
    """An injected write-back push failure rides the transient-retry
    policy like an apiserver blip: the bind still lands live, and the
    exercised-fault counter proves the retry path ran."""
    from ksim_tpu.faults import FAULTS
    from ksim_tpu.syncer.writeback import LiveWriteBack

    class FakeSource:
        def __init__(self):
            self.bound = []

        def bind_pod(self, ns, name, node):
            self.bound.append((ns, name, node))

        def patch_pod_annotations(self, ns, name, ann):
            pass

        def get_pod(self, ns, name):
            return {"metadata": {"name": name}}

        def delete_pod(self, ns, name, uid=""):
            pass

    monkeypatch.setattr(LiveWriteBack, "RETRY_DELAY_S", 0.05)
    FAULTS.reset()
    FAULTS.arm("writeback.push", "call:1")
    store = ClusterStore()
    store.create("pods", make_pod("p1"))
    src = FakeSource()
    wb = LiveWriteBack(src, store).start()
    try:
        store.patch(
            "pods", "p1", "default",
            lambda o: o["spec"].__setitem__("nodeName", "n1"),
        )
        deadline = time.time() + 30
        while time.time() < deadline and not src.bound:
            time.sleep(0.05)
        assert FAULTS.fired("writeback.push") == 1, "fault never exercised"
        assert src.bound == [("default", "p1", "n1")]
    finally:
        wb.stop()
        FAULTS.reset()


def test_kubeapi_request_fault_site():
    """The kubeapi site fires before the wire (no cooperating server
    needed); once disarmed the real transport path resumes and fails
    with its own classified error, not the injected one."""
    import pytest

    from ksim_tpu.faults import FAULTS, InjectedFault
    from ksim_tpu.syncer.kubeapi import KubeApiError, KubeApiSource

    FAULTS.reset()
    FAULTS.arm("kubeapi.request", "call:1")
    src = KubeApiSource("http://127.0.0.1:1", request_timeout=2.0)
    try:
        with pytest.raises(InjectedFault):
            src.get_pod("default", "p1")
        assert FAULTS.fired("kubeapi.request") == 1
        assert isinstance(InjectedFault("x"), SimulatorError)
        with pytest.raises(KubeApiError):
            src.get_pod("default", "p1")  # fault cleared: real path resumes
    finally:
        FAULTS.reset()
