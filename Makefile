# Developer entrypoints (the reference's Makefile analogue).

PY ?= python

.PHONY: test test-tpu bench serve lint

test:
	$(PY) -m pytest tests/ -q --deselect tests/test_tpu_parity.py

test-tpu:
	$(PY) -m pytest tests/test_tpu_parity.py -q -rs

bench:
	$(PY) bench.py

serve:
	$(PY) -m ksim_tpu.cmd.simulator

lint:
	$(PY) -m compileall -q ksim_tpu
