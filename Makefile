# Developer entrypoints (the reference's Makefile analogue).

PY ?= python

.PHONY: test test-tpu bench bench-tpu perf-table serve lint lock-check faults trace jobs restart-check shard-check mesh-check obs-check stream-check

test:
	$(PY) -m pytest tests/ -q --deselect tests/test_tpu_parity.py

# One-command behavior-lock verification: the FULL 50k churn stream
# through both the per-pass and device-resident paths, asserting the
# 52781/42829 counts stepwise (repo CLAUDE.md) — with the incremental
# lower-cache + double-buffered prelower fully ON (round 10), plus the
# counter-based O(delta) guard (steady-state featurize rows scale with
# window events, not universe size) — and the FLEET parity lock (round
# 12): 8 lanes x 6k events through the vmapped fleet path, every lane
# byte-identical to 2524/471 with the shared universe lowered once per
# window (counter-based guard).  ~15-25 min on CPU.  Round 15 adds the
# CHAOS leg: the locked 6k prefix with injected device-dispatch faults
# mid-stream — the breaker trips, the half-open probe recovers the
# device path, and the 2524/471 counts still hold byte-identically.
# The analyzer gates the lock run: a lock/kernel/registry contract
# violation is exactly the class of bug the 50k stepwise run exists to
# catch, and lint finds it in seconds instead of minutes.  Round 17
# adds the SHARDED legs: the locked 6k prefix and the full 50k stream
# replayed over a tp=8 virtual mesh (8 host devices), every step
# byte-identical to the solo counts with zero shard_mesh fallbacks.
lock-check: lint
	$(PY) -m pytest tests/test_behavior_locks.py::test_churn_lock_50k_stepwise_device_vs_per_pass tests/test_behavior_locks.py::test_churn_fleet_lock_6k_lanes8 tests/test_behavior_locks.py::test_churn_lock_6k_holds_under_dispatch_faults_with_recovery tests/test_behavior_locks.py::test_churn_lock_6k_sharded_tp8 tests/test_behavior_locks.py::test_churn_lock_50k_stepwise_sharded_tp8 -q -rs -m slow

# Sharded-replay verification (docs/scaling.md "Sharded device
# replay"): the fast tier-1 sharded-vs-solo parity matrix (byte parity
# on churn + full-record annotations + preemption, the explicit-mesh
# contract, dead-device containment, the prewarm plane, and the bench
# churn_shard rung) plus the slow 6k sharded lock leg.  Gated on lint
# for the same reason lock-check is.
shard-check: lint
	$(PY) -m pytest tests/test_replay_device.py tests/test_replay_cache.py -q -k "sharded or prewarm"
	$(PY) -m pytest tests/test_bench.py -q -k "churn_shard and not fleet"
	$(PY) -m pytest tests/test_behavior_locks.py::test_churn_lock_6k_sharded_tp8 -q -rs -m slow

# The 2-D mesh suite (round 19, docs/scaling.md "2-D mesh"): the tp x dp
# fleet parity tests + the donated-carry byte-identity test, the
# churn_fleet_shard bench rung evidence (counts_match, the (2, 4) grid,
# per-shard bytes, dev_const zero-resharding counters), and the slow
# tp=4 x dp=2 6k fleet lock leg — every lane 2524/471 stepwise against
# the solo unsharded run.  Gated on lint like shard-check; the bench
# children run themselves in tests/helpers.sanitized_cpu_env.
mesh-check: lint
	$(PY) -m pytest tests/test_replay_device.py -q -k "tp_dp or donation"
	$(PY) -m pytest tests/test_bench.py -q -k "churn_fleet_shard"
	$(PY) -m pytest tests/test_behavior_locks.py::test_churn_fleet_lock_6k_tp4_dp2 -q -rs -m slow

# The fault suite (docs/faults.md) on CPU in the sanitized environment
# (tests/helpers.sanitized_cpu_env drops the axon sitecustomize that
# wedges jax init on a dead chip) — runnable under ANY hardware state.
# -m '' overrides pyproject's default -m 'not slow' so the slow-marked
# 6k fault schedules run here too (the full five-schedule matrix).
# KSIM_STORE_STRICT=1: the sanitizer-lite store mode (docs/env.md) is
# on for the whole fault matrix — an injected fault whose containment
# path touched the store without the lock would fail loudly here.
faults:
	$(PY) -c "import subprocess, sys; from tests.helpers import sanitized_cpu_env; \
	sys.exit(subprocess.call([sys.executable, '-m', 'pytest', \
	'tests/test_replay_faults.py', 'tests/test_fault_injection.py', \
	'tests/test_replay_cache.py', 'tests/test_jobs.py', \
	'tests/test_jobs_durability.py', \
	'-q', '-m', ''], env=sanitized_cpu_env({'KSIM_STORE_STRICT': '1'})))"

# The job-plane suite (docs/jobs.md) on CPU in the sanitized env, slow
# tests included (-m '' overrides the default 'not slow'): lifecycle
# over HTTP, queue backpressure, cancel-mid-segment rollback, SSE
# progress, the shared compile cache, and the per-tenant fault
# containment matrix (KSIM_JOBS_FAULTS).
jobs:
	$(PY) -c "import subprocess, sys; from tests.helpers import sanitized_cpu_env; \
	sys.exit(subprocess.call([sys.executable, '-m', 'pytest', \
	'tests/test_jobs.py', '-q', '-m', ''], env=sanitized_cpu_env()))"

# Crash-recovery verification (docs/jobs.md "Durability & recovery"):
# the journal/AOT-cache unit matrix (torn tails, corrupt CRCs, corrupt
# serialized executables — all hand-written bad bytes), manager replay
# on restart, the SSE aborted-reader leak regression, the round-16
# incremental-resume matrix (crash after EVERY checkpoint boundary,
# torn/corrupt checkpoint fallback, append-fault containment, gap-free
# recovered SSE backlogs), the round-20 fleet matrix (lease claim
# races, takeover epochs, release tombstones, shared-journal
# interleaved appenders + cross-process compaction), and the slow
# SIGKILL end-to-ends — interrupted-marking, checkpoint-resume, and
# the kill-a-worker fleet fail-over, all on the locked 6k stream
# (-m '' includes them).  Runs in the sanitized CPU env so it works
# under ANY hardware condition.
restart-check: lint
	$(PY) -c "import subprocess, sys; from tests.helpers import sanitized_cpu_env; \
	sys.exit(subprocess.call([sys.executable, '-m', 'pytest', \
	'tests/test_jobs_durability.py', '-q', '-m', ''], env=sanitized_cpu_env()))"

# Trace-plane validation (docs/observability.md): the locked 6k prefix
# through the device path with KSIM_TRACE_OUT set, in the sanitized CPU
# env — asserts the counts hold under tracing and the emitted Chrome
# trace parses with every expected phase span, then an armed-fault run
# asserting the fault/fallback timeline events, and (run 5) a 2-worker
# fleet leg whose SIGTERM-published trace exports must merge into one
# Chrome trace with a lane per worker and a complete submit->claim->run
# flow triple per job.  Stdlib-only parent.
trace:
	$(PY) tools/trace_check.py

# Fleet observability verification (docs/observability.md "Fleet
# observability"): the histogram bucket-merge property test, the
# Prometheus exposition golden + round-trip parser tests, crash-atomic
# publish, staleness flagging, the merged-trace lane/flow tests, and
# the slow 2-process fleet scrape end-to-end (-m '' includes it).
# Sanitized CPU env, so it runs under ANY hardware condition; gated on
# lint because METRIC_NAMES/registry drift is exactly what the
# analyzer catches in seconds.
obs-check: lint
	$(PY) -c "import subprocess, sys; from tests.helpers import sanitized_cpu_env; \
	sys.exit(subprocess.call([sys.executable, '-m', 'pytest', \
	'tests/test_obs_fleet.py', '-q', '-m', ''], env=sanitized_cpu_env()))"

# Streaming-ingest verification (docs/scenario.md "Streaming ingest"):
# the windowed-vs-materialized byte-identity suite (selector == batch
# resample on shuffled input, window-boundary splits, producer-fault
# degradation, mid-read bound refusal), the streaming behavior-lock leg
# (borg_mini through tiny windows on both paths), and the churn_stream
# bench rung evidence (mid-run RSS watermark, events/sec, counts_match,
# dead-device one-JSON-line).  Sanitized CPU env so it runs under ANY
# hardware condition; gated on lint because the trace-ingest
# thread-role and the traces.stream span/site registrations are
# exactly what the analyzer checks.
stream-check: lint
	$(PY) -c "import subprocess, sys; from tests.helpers import sanitized_cpu_env; \
	sys.exit(subprocess.call([sys.executable, '-m', 'pytest', \
	'tests/test_traces_stream.py', \
	'tests/test_behavior_locks.py::test_trace_lock_borg_mini_holds_with_streaming_ingest', \
	'-q'], env=sanitized_cpu_env()))"
	$(PY) -c "import subprocess, sys; from tests.helpers import sanitized_cpu_env; \
	sys.exit(subprocess.call([sys.executable, '-m', 'pytest', \
	'tests/test_bench.py', '-q', '-k', 'churn_stream'], \
	env=sanitized_cpu_env()))"

test-tpu:
	$(PY) -m pytest tests/test_tpu_parity.py -q -rs

bench:
	$(PY) bench.py

# One command to refresh TPU perf records the moment the chip is alive:
# runs the full ladder + churn on the default (TPU) backend, saves the
# JSON line as a dated local record, and regenerates the README table.
bench-tpu:
	$(PY) bench.py --budget 2400 2>bench_tpu.log | tail -1 \
	  > BENCH_local_tpu_$$(date +%Y%m%d).json
	@grep -q '"platform": "tpu"' BENCH_local_tpu_$$(date +%Y%m%d).json \
	  || echo "WARNING: record is not from the TPU backend (chip wedged?)"
	$(PY) tools/perf_table.py --update

perf-table:
	$(PY) tools/perf_table.py --update

serve:
	$(PY) -m ksim_tpu.cmd.simulator

# Static contract analysis (docs/lint.md): compile the tree, then run
# the AST analyzer over ksim_tpu/, bench.py and tools/ — exits nonzero
# on any unsuppressed finding.  tools/ksimlint is stdlib-only (it never
# imports jax, numpy or ksim_tpu), so this is safe under ANY hardware
# condition, including the wedged-tunnel environments bench guards
# against — no sanitized env needed.
lint:
	$(PY) -m compileall -q ksim_tpu tools bench.py
	$(PY) -m tools.ksimlint
