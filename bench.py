"""Benchmark ladder: pod-node pairs scored per second (BASELINE.md configs).

Runs the full sequential-commit scheduling scan (every pod x node pair
filtered AND scored by every enabled plugin, with capacity/topology commit
between pods) and the one-shot record="full" batch evaluation (the
product's recorded-results path) over a ladder of cluster sizes ending at
the BASELINE config-4 shape (10k pods x 5k nodes), plus the config-5
50k-event churn replay.

The headline runs in EXACT mode — x64 enabled, so the int64/float64
scoring paths are active and final scores are bit-exact vs the upstream
plugins (XLA emulates s64/f64 on TPU; verified by tests/tpu_parity_main.py
on a real v5e).  Each rung also reports the float32 fast mode (documented
±1 rounding tolerance at integer-ratio boundaries) as
``sched_pairs_per_sec_f32``.

Crash containment (the round-1/round-2 driver failures):

- The parent process imports ONLY the stdlib — never jax.  On this image a
  wedged TPU makes jax backend init block indefinitely even with
  ``JAX_PLATFORMS=cpu`` (the axon sitecustomize on PYTHONPATH touches the
  dead chip), so anything the parent must guarantee cannot depend on jax
  importing.
- The backend is probed in a subprocess under a hard watchdog.  If the
  default (TPU) backend does not come up, the ladder falls back to CPU in
  a sanitized environment (axon dropped from PYTHONPATH,
  ``JAX_PLATFORMS=cpu``) so a recorded number exists under ANY chip state.
- Every rung runs in its own subprocess with its own timeout: a TPU
  worker kernel fault (the BENCH_r01.json crash) or a hang loses that one
  rung, not the run.
- The final JSON line is guaranteed: partial results are flushed to
  ``bench_partial.json`` after every rung, and SIGTERM/SIGINT/atexit all
  route to a print-once emitter, so an external ``timeout`` kill still
  yields a parseable stdout line.
- A wall-clock budget (``BENCH_BUDGET_S``, default 1500 s) stops new rungs
  in time to emit the line before any external watchdog fires.

Prints ONE JSON line with the headline metric (exact sequential-scan
pairs/sec at the largest completed rung):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N/50000, "rungs": {...}}
Baseline: >= 50k pairs/sec north star (BASELINE.json).
"""

from __future__ import annotations

import argparse
import atexit
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import traceback

LADDER = [(1_000, 200), (5_000, 1_000), (10_000, 5_000)]
# Fallback ladder when the chip is dead: CPU finishes 5000x1000 exact in
# seconds (warm cache).  The 10000x5000 rung runs SLICED on CPU (below)
# rather than timing out: the full sequential scan exceeds its cap there.
CPU_LADDER = [(1_000, 200), (5_000, 1_000)]
# CPU bounds the 10kx5k MEASUREMENT, not the rung (round-3 verdict): the
# full 10k-pod cluster is generated and featurized, and the scan+batch
# timing runs over the first CPU_SLICE_PODS queue pods x all 5k nodes —
# a measured pairs/s record for the north-star shape on any platform.
CPU_SLICE_PODS = 2_000
# Churn size CPU replays inside the stage cap (events, nodes): the FULL
# config-5 shape — ~176 s measured on this image's CPU (round-3), well
# under CHURN_TIMEOUT; used by both fallback paths.
CPU_CHURN_CAP = (50_000, 2_000)

# Per-stage subprocess timeouts (seconds).  Cold XLA compiles of the
# large-shape scan programs cost 5-60 s each; the persistent compile cache
# (~/.cache/ksim_tpu/jax) makes reruns much faster.
PROBE_TIMEOUT = 90
RUNG_TIMEOUT = {"1000x200": 420, "5000x1000": 480, "10000x5000": 600}
CPU_RUNG_TIMEOUT = 420
CHURN_TIMEOUT = 900
CHURN_EXACT_TIMEOUT = 420
EMIT_RESERVE = 20  # seconds kept back for collection + emit

_REPO = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# Child payloads (these import jax; they only ever run in subprocesses).
# ---------------------------------------------------------------------------


def _child_setup() -> None:
    import jax

    from ksim_tpu.util import enable_compilation_cache, raise_map_count_limit

    # One-time-per-machine XLA compiles, shared across rung subprocesses.
    enable_compilation_cache()
    # Long children (the 50k churn replay) compile/load many programs in
    # one process; vm.max_map_count's 65530 default kills exactly that.
    raise_map_count_limit()
    # Exact mode for the headline: int64/float64 scoring paths active.
    jax.config.update("jax_enable_x64", True)


def child_probe() -> dict:
    from ksim_tpu.errors import DeviceUnavailableError

    try:
        import jax

        devs = jax.devices()
    except Exception as e:
        # Classify backend-init failures as the sentinel the rest of the
        # repo uses for a dead/wedged accelerator, so the parent's error
        # record carries provenance ("DeviceUnavailableError: ...").
        raise DeviceUnavailableError(f"backend init failed: {e}") from e
    return {"platform": devs[0].platform, "device_count": len(devs)}


def child_rung(
    n_pods: int, n_nodes: int, seed: int, repeats: int, slice_pods: int = 0
) -> dict:
    import jax

    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer
    from tests.helpers import random_cluster

    _child_setup()
    t0 = time.perf_counter()
    nodes, pods = random_cluster(seed, n_nodes=n_nodes, n_pods=n_pods, bound_fraction=0.0)
    t1 = time.perf_counter()
    # slice_pods bounds the MEASUREMENT, not the cluster: the workload is
    # still the full config shape, but scan/batch timing covers the first
    # ``slice_pods`` queue pods over ALL nodes — the measured pairs/s for
    # the completed slice (how a platform too slow for the full rung still
    # produces a recorded number; round-3 verdict item 2).
    sliced = 0 < slice_pods < n_pods
    queue = pods[:slice_pods] if sliced else pods
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
    t2 = time.perf_counter()
    print(
        f"[{n_pods}x{n_nodes}] gen {t1-t0:.1f}s featurize {t2-t1:.1f}s; padded "
        f"P={feats.pods.valid.shape[0]} N={feats.nodes.padded} "
        f"{'slice=' + str(len(queue)) + ' ' if sliced else ''}"
        f"on {jax.devices()[0].platform}",
        file=sys.stderr,
        flush=True,
    )
    pairs = len(queue) * n_nodes

    # Sequential-commit scan (the real scheduling semantics), exact mode
    # (x64 active) — headline.
    eng = Engine(feats, default_plugins(feats), record="selection")
    eng.schedule()  # compile + warmup
    times = []
    for _ in range(repeats):
        t = time.perf_counter()
        res, _state = eng.schedule(pull_state=False)
        times.append(time.perf_counter() - t)
    sched_s = min(times)

    # float32 fast mode (same kernels, f32 normalize/score paths).
    jax.config.update("jax_enable_x64", False)
    try:
        eng32 = Engine(feats, default_plugins(feats), record="selection")
        eng32.schedule()
        times = []
        for _ in range(repeats):
            t = time.perf_counter()
            eng32.schedule(pull_state=False)
            times.append(time.perf_counter() - t)
        sched32_s = min(times)
    finally:
        jax.config.update("jax_enable_x64", True)

    # Fused one-dispatch batch in the SAME record mode as the headline
    # scan (selection, exact) — the apples-to-apples batch-vs-scan
    # column the round-4 verdict asked for.  lax.map over vmap blocks
    # keeps plugin intermediates on-chip (evaluate_batch_fused).
    eng.evaluate_batch_fused()  # compile + warmup
    times = []
    for _ in range(repeats):
        t = time.perf_counter()
        eng.evaluate_batch_fused()
        times.append(time.perf_counter() - t)
    batch_sel_s = min(times)

    # One-shot batch evaluation, record="full": materializes every filter
    # reason / raw score / final score matrix (the product's recorded
    # results) on device, streamed chunk by chunk, pulling each chunk's
    # selection decisions to the host (the dense result tensors stay
    # device-resident for on-demand decode — transferring all ~9GB at
    # this shape is not part of the eval path).
    import numpy as np

    engb = Engine(feats, default_plugins(feats), record="full")

    def batch_pass():
        for _s, out in engb.evaluate_batch_chunks():
            np.asarray(out["selected"])
            jax.block_until_ready(out)

    batch_pass()  # compile + warmup
    times = []
    for _ in range(repeats):
        t = time.perf_counter()
        batch_pass()
        times.append(time.perf_counter() - t)
    batch_s = min(times)

    n_sched = int((res.selected >= 0).sum())
    rung = {
        "sched_pairs_per_sec": round(pairs / sched_s),
        "sched_pairs_per_sec_f32": round(pairs / sched32_s),
        "batch_pairs_per_sec": round(pairs / batch_s),
        "batch_pairs_per_sec_selection": round(pairs / batch_sel_s),
        "sched_s": round(sched_s, 3),
        "sched_f32_s": round(sched32_s, 3),
        "batch_s": round(batch_s, 3),
        "batch_sel_s": round(batch_sel_s, 3),
        "pods_scheduled": n_sched,
        "exact": True,
        "platform": jax.devices()[0].platform,
    }
    if sliced:
        rung["slice_pods"] = len(queue)
        rung["pairs_measured"] = pairs
    print(
        f"[{n_pods}x{n_nodes}] scan-exact {sched_s*1e3:.0f}ms "
        f"({pairs/sched_s/1e6:.2f}M pairs/s, {n_sched} placed), "
        f"scan-f32 {sched32_s*1e3:.0f}ms ({pairs/sched32_s/1e6:.2f}M pairs/s), "
        f"batch-sel {batch_sel_s*1e3:.0f}ms ({pairs/batch_sel_s/1e6:.2f}M pairs/s), "
        f"batch-full {batch_s*1e3:.0f}ms ({pairs/batch_s/1e6:.2f}M pairs/s)",
        file=sys.stderr,
        flush=True,
    )
    return rung


def child_churn(
    seed: int,
    n_nodes: int,
    n_events: int,
    exact: bool = False,
    device: bool = False,
    preempt: bool = False,
    record_full: bool = False,
) -> dict:
    """BASELINE config 5: churn replay — rolling pod arrivals/completions
    + node drain/replace over the full default plugin set, sequential
    scheduling semantics per step.  The full rung runs in float32 fast
    mode: this rung measures end-to-end wall-clock over ~500 scheduling
    passes, where the x64-emulation overhead compounds ~10x — score
    exactness is covered by the ladder rungs and the TPU parity tier.
    Both modes are platform-deterministic and land on the same counts
    (seed 0/2000 nodes: 6k events -> 2524/471, 50k -> 52781/42829 —
    tests/test_behavior_locks.py pins the 6k prefix); ``exact`` runs a
    bounded x64 replay so the driver record carries mode-identical
    counts next to the f32 wall-clock number."""
    import jax

    from ksim_tpu.scenario import ScenarioRunner, churn_scenario

    _child_setup()
    jax.config.update("jax_enable_x64", bool(exact))
    # Cap the per-pass pod batch and coarsen the pod bucket: the pending
    # pool under saturation otherwise wanders through every power-of-two
    # bucket up to 16384, and each new shape is another multi-second XLA
    # compile (upstream schedules one pod per cycle; capping a batch just
    # leaves the rest queued).
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=device,
        preemption=preempt,
        record="full" if record_full else "selection",
    )
    res = runner.run(
        churn_scenario(seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100)
    )
    out = {
        "events": res.events_applied,
        "wall_s": round(res.wall_seconds, 1),
        "events_per_sec": round(res.events_per_second),
        "pods_scheduled": res.pods_scheduled,
        "unschedulable_attempts": res.unschedulable_attempts,
        "steps": len(res.steps),
        "exact": bool(exact),
        "preemption": bool(preempt),
        "record": "full" if record_full else "selection",
        "platform": jax.devices()[0].platform,
    }
    if res.phase_seconds:
        # Per-phase wall-clock split (trace plane, obs.SPAN_NAMES keys):
        # where inside the replay the time went — device lower/dispatch/
        # reconcile vs the per-pass host path (runner.step, which nests
        # its service.schedule span).  The stdlib-only parent passes the
        # child JSON through untouched, so this rides to the one-line
        # record for free.
        out["phases"] = {
            name: {"seconds": res.phase_seconds[name], "count": res.phase_counts[name]}
            for name in sorted(res.phase_seconds)
        }
    if device and runner.replay_driver is not None:
        # Dispatch evidence: the per-pass path pays one engine round-trip
        # group (pack + scan + pull) per scheduling pass; the device path
        # pays one per SEGMENT plus one per fallback step.  The fallback
        # histogram (SegmentLowerer reject reasons) and the on-device
        # step fraction track tensor-vocabulary coverage across rounds.
        # Since round 10 drv.stats() also carries the incremental-
        # lowering evidence next to the phases split above: lower_cache
        # hits/misses/invalidations, featurize_calls (fresh per-pod row
        # builds — the O(delta) counter `make lock-check` guards),
        # prelower pipeline counters, and dev_const transfer reuse.
        drv = runner.replay_driver
        round_trips = drv.device_round_trips + drv.fallback_steps
        # drv.stats() carries the dispatch counters PLUS the round-8
        # failure-containment evidence: device_errors = dispatches
        # degraded to the host path, watchdog_timeouts its hung subset,
        # breaker_tripped = the sticky circuit breaker disabled the
        # device path mid-run.  All of it flows from the KSIM_FAULTS /
        # KSIM_REPLAY_* environment, so the stdlib-only parent can arm
        # chaos runs without importing anything.
        out.update(
            device=True,
            device_step_fraction=(
                round(drv.device_steps / len(res.steps), 4) if res.steps else None
            ),
            per_pass_round_trips=len(res.steps),
            dispatch_reduction=(
                round(len(res.steps) / round_trips, 1) if round_trips else None
            ),
            **drv.stats(),
        )
    print(
        f"[churn {n_events}ev/{n_nodes}n"
        f"{' exact' if exact else ''}{' device' if device else ''}"
        f"{' preempt' if preempt else ''}{' full' if record_full else ''}] "
        f"{res.wall_seconds:.1f}s "
        f"({res.events_per_second:.0f} ev/s, {res.pods_scheduled} scheduled)",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_shard(
    seed: int, n_nodes: int, n_events: int, shard_tp: int
) -> dict:
    """Sharded device replay rung (round 17, KSIM_REPLAY_TP): the SAME
    churn stream through the device path at tp=1 and tp=``shard_tp``
    (the node axis laid over a dp=1 mesh) in ONE child, so the two
    walls share a process, a backend state and a warmed jax runtime.
    Evidence the record must carry: byte-identical counts and device
    coverage between the widths (``counts_match``/``device_steps_match``
    — GSPMD value-preservation is the product claim), each width's
    fallback histogram with zero ``shard_mesh`` entries, the per-shard
    full-record byte budget from the lower log, and the per-chip
    device-memory watermark next to the phases split (the 100k-node
    memory story is per-chip, not per-host).  On a CPU host the tp mesh
    runs on forced virtual devices; on a host with fewer devices than
    the mesh the tp leg degrades through the device-error ladder and
    the record says so — the JSON line exists under any hardware
    condition."""
    # The virtual mesh must exist BEFORE jax initializes its backend —
    # harmless on real multi-device hosts (the flag only affects the
    # host platform).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    from ksim_tpu.scenario import ScenarioRunner, churn_scenario

    _child_setup()
    jax.config.update("jax_enable_x64", False)

    def per_chip_peak() -> "dict | None":
        """Per-device peak_bytes_in_use, when the backend exposes it
        (TPU does; CPU returns None) — guarded so a backend without
        memory_stats never breaks the one-JSON-line contract."""
        stats = {}
        for d in jax.devices():
            try:
                ms = d.memory_stats()
            except Exception:
                return None
            if not ms or "peak_bytes_in_use" not in ms:
                return None
            stats[str(d.id)] = int(ms["peak_bytes_in_use"])
        return stats

    out: dict = {"shard_tp": shard_tp, "modes": {}}
    sigs = {}
    for tp in (1, shard_tp):
        os.environ["KSIM_REPLAY_TP"] = str(tp)
        runner = ScenarioRunner(
            max_pods_per_pass=1024,
            pod_bucket_min=128,
            device_replay=True,
            preemption=True,
        )
        res = runner.run(
            churn_scenario(
                seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100
            )
        )
        drv = runner.replay_driver
        mode: dict = {
            "wall_s": round(res.wall_seconds, 1),
            "events_per_sec": round(res.events_per_second),
            "pods_scheduled": res.pods_scheduled,
            "unschedulable_attempts": res.unschedulable_attempts,
            "device_steps": drv.device_steps,
            "fallback_steps": drv.fallback_steps,
            "unsupported": dict(drv.unsupported),
            "lowered_tps": sorted({e["tp"] for e in drv.lower_log}),
            "full_bytes_per_shard_max": max(
                (e["full_bytes_per_shard"] for e in drv.lower_log), default=0
            ),
        }
        if res.phase_seconds:
            mode["phases"] = {
                name: {
                    "seconds": res.phase_seconds[name],
                    "count": res.phase_counts[name],
                }
                for name in sorted(res.phase_seconds)
            }
        mode["per_chip_peak_bytes"] = per_chip_peak()
        out["modes"][f"tp{tp}"] = mode
        sigs[tp] = (
            res.pods_scheduled,
            res.unschedulable_attempts,
            [(s.step, s.scheduled, s.unschedulable) for s in res.steps],
        )
        print(
            f"[churn_shard tp={tp} {n_events}ev/{n_nodes}n] "
            f"{res.wall_seconds:.1f}s ({res.pods_scheduled} scheduled, "
            f"{drv.device_steps} device steps)",
            file=sys.stderr,
            flush=True,
        )
    out["counts_match"] = sigs[1] == sigs[shard_tp]
    out["device_steps_match"] = (
        out["modes"]["tp1"]["device_steps"]
        == out["modes"][f"tp{shard_tp}"]["device_steps"]
    )
    out["platform"] = jax.devices()[0].platform
    return out


def child_churn_fleet(seed: int, n_nodes: int, n_events: int, lanes: int) -> dict:
    """Fleet replay rung (engine/fleet.py): the SAME churn stream on S
    independent trajectories, one vmapped device dispatch per window,
    shared universe lowered once.  Runs the solo device replay first so
    the record carries the aggregate-throughput comparison the fleet
    exists for: ``aggregate_speedup = lanes * solo_wall / fleet_wall``
    (>= 3x at S=8 is the round-12 target), plus per-lane counts (every
    lane must land the solo counts — the parity lock's bench twin), the
    lanes-on-device fraction, and the cohort leader's lower_cache /
    prelower / dev_const evidence (the lowered-once claim, readable
    straight from this record)."""
    import time

    import jax

    from ksim_tpu.scenario import ScenarioRunner, churn_scenario

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    kw = dict(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        preemption=True,
    )

    def stream():
        return churn_scenario(
            seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100
        )

    # One untimed warm-up replay first: the timed solo run would
    # otherwise carry all jit tracing/compilation that the in-process
    # fleet run then reuses for free (dedupe mode dispatches the very
    # same compiled program), biasing aggregate_speedup upward — both
    # timed runs must start equally warm for the comparison to mean
    # anything.
    ScenarioRunner(**kw).run(stream())
    t0 = time.perf_counter()
    solo = ScenarioRunner(**kw)
    rs = solo.run(stream())
    solo_wall = time.perf_counter() - t0
    t1 = time.perf_counter()
    fleet = ScenarioRunner(**kw, fleet=lanes)
    rf = fleet.run(stream())
    fleet_wall = time.perf_counter() - t1
    leader = max(
        (ln.driver for ln in fleet.fleet_lanes), key=lambda d: len(d.lower_log)
    )
    out = {
        "events": n_events,
        "nodes": n_nodes,
        "lanes": lanes,
        "solo_wall_s": round(solo_wall, 1),
        "fleet_wall_s": round(fleet_wall, 1),
        "trajectories_per_sec": round(lanes / fleet_wall, 3) if fleet_wall else None,
        "aggregate_speedup": (
            round(lanes * solo_wall / fleet_wall, 2) if fleet_wall else None
        ),
        "solo_counts": [rs.pods_scheduled, rs.unschedulable_attempts],
        "lane_counts": [
            [r.pods_scheduled, r.unschedulable_attempts] for r in rf.lanes
        ],
        "lanes_match_solo": all(
            (r.pods_scheduled, r.unschedulable_attempts)
            == (rs.pods_scheduled, rs.unschedulable_attempts)
            for r in rf.lanes
        ),
        "fleet": fleet.fleet_driver.stats(),
        "platform": jax.devices()[0].platform,
        # The cohort leader's incremental-lowering evidence: with S
        # convergent lanes, lower_cache hits + lane_lowerings==[N,0,...]
        # in "fleet" above IS the lowered-once-per-window guard.
        "lower_cache": leader.stats()["lower_cache"],
        "prelower": leader.stats()["prelower"],
        "dev_const": leader.stats()["dev_const"],
    }
    if rf.phase_seconds:
        out["phases"] = {
            name: {"seconds": rf.phase_seconds[name], "count": rf.phase_counts[name]}
            for name in sorted(rf.phase_seconds)
        }
    print(
        f"[churn_fleet {n_events}ev/{n_nodes}n x{lanes}] solo {solo_wall:.1f}s, "
        f"fleet {fleet_wall:.1f}s ({out['aggregate_speedup']}x aggregate, "
        f"lanes_on_device {out['fleet']['lanes_on_device']})",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_fleet_shard(
    seed: int, n_nodes: int, n_events: int, lanes: int, tp: int
) -> dict:
    """2-D mesh fleet rung (round 19): the SAME churn stream on
    ``lanes`` trajectories laid over the dp axis of a (dp, tp) fleet
    mesh while every lane's node tensors shard over tp — one vmapped,
    GSPMD-partitioned dispatch per window — next to the SOLO unsharded
    device replay of the same stream.  Evidence the record must carry:
    the solo-vs-fleet walls and aggregate speedup (the cond-gated
    preemption restructure is what makes vmap >= solo-per-lane
    possible — docs/scaling.md "2-D mesh (round 19)"), per-lane counts
    with a ``counts_match`` flag (every lane must land the solo
    counts), the (dp, tp) grids actually built, the leader's lowered
    tp widths and per-shard full-record byte budget, and the leader's
    dev_const hit/miss counters — steady-state segments re-transfer
    NOTHING when the committed fleet layout is adopted, so misses must
    flatten after the first dispatch (the zero-resharding claim).  On
    a host with fewer than lanes*tp devices the fleet leg degrades
    through the device-error ladder and the record says so — the JSON
    line exists under any hardware condition."""
    # The virtual (dp, tp) grid must exist BEFORE jax initializes its
    # backend — harmless on real multi-device hosts.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import time

    import jax

    from ksim_tpu.scenario import ScenarioRunner, churn_scenario

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    # 4-step windows: the zero-resharding claim is about STEADY-STATE
    # segments, and the dev-const reuse ladder needs three windows to
    # fully engage (window 1 runs before the backend probe enables
    # collection, window 2 builds the reuse map, window 3+ hits it) —
    # the default 16-step window would need a 4800-event stream before
    # the counters could move at all.
    kw = dict(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        preemption=True,
        device_segment_steps=4,
    )

    def stream():
        return churn_scenario(
            seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100
        )

    # Solo leg runs unsharded and un-fleeted; scrub the knobs in case
    # the orchestrator's env carries them.  One untimed warm-up first —
    # both timed legs must start equally warm (see child_churn_fleet).
    os.environ.pop("KSIM_REPLAY_TP", None)
    os.environ.pop("KSIM_FLEET_DP", None)
    ScenarioRunner(**kw).run(stream())
    t0 = time.perf_counter()
    solo = ScenarioRunner(**kw)
    rs = solo.run(stream())
    solo_wall = time.perf_counter() - t0

    os.environ["KSIM_FLEET_DP"] = str(lanes)
    os.environ["KSIM_REPLAY_TP"] = str(tp)
    t1 = time.perf_counter()
    fleet = ScenarioRunner(**kw, fleet=lanes)
    rf = fleet.run(stream())
    fleet_wall = time.perf_counter() - t1
    leader = max(
        (ln.driver for ln in fleet.fleet_lanes), key=lambda d: len(d.lower_log)
    )
    fd = fleet.fleet_driver
    with fd._mesh_lock:
        grids = sorted(fd._mesh)
        mesh_failed = fd._mesh_failed
    out = {
        "events": n_events,
        "nodes": n_nodes,
        "lanes": lanes,
        "tp": tp,
        "solo_wall_s": round(solo_wall, 1),
        "fleet_wall_s": round(fleet_wall, 1),
        "aggregate_speedup": (
            round(lanes * solo_wall / fleet_wall, 2) if fleet_wall else None
        ),
        "solo_counts": [rs.pods_scheduled, rs.unschedulable_attempts],
        "lane_counts": [
            [r.pods_scheduled, r.unschedulable_attempts] for r in rf.lanes
        ],
        "counts_match": all(
            (r.pods_scheduled, r.unschedulable_attempts)
            == (rs.pods_scheduled, rs.unschedulable_attempts)
            for r in rf.lanes
        ),
        "mesh_grids": [list(g) for g in grids],
        "mesh_failed": mesh_failed,
        "lowered_tps": sorted({e["tp"] for e in leader.lower_log}),
        "full_bytes_per_shard_max": max(
            (e["full_bytes_per_shard"] for e in leader.lower_log), default=0
        ),
        "fleet": fd.stats(),
        # Zero-resharding evidence: after the first fleet dispatch
        # adopts the ("mesh", dp, tp) layout, steady-state windows hit
        # the id-keyed dev-const reuse map — misses stay flat while
        # hits grow with the window count.
        "dev_const": leader.stats()["dev_const"],
        "platform": jax.devices()[0].platform,
    }
    print(
        f"[churn_fleet_shard {n_events}ev/{n_nodes}n x{lanes} tp={tp}] "
        f"solo {solo_wall:.1f}s, fleet {fleet_wall:.1f}s "
        f"({out['aggregate_speedup']}x aggregate, grids {out['mesh_grids']}, "
        f"counts_match {out['counts_match']})",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_jobs(
    seed: int, n_nodes: int, n_events: int, n_jobs: int, workers: int
) -> dict:
    """Job-plane rung (ksim_tpu/jobs): ``n_jobs`` concurrent copies of
    the churn stream submitted as tenant scenario documents through the
    bounded queue onto a ``workers``-wide pool, every job on the device
    path.  Evidence the record must carry: sustained jobs/min, per-job
    p50/p99 latency FROM EACH JOB'S PRIVATE trace plane, per-job
    scheduled/unschedulable counts with a ``jobs_match_solo`` flag (a
    solo replay of the same stream runs AFTER the fleet of jobs — the
    jobs must start cold so the compile-once proof is about THEM), and
    the process-wide ``compile_cache`` counters: ``shared_rungs`` >= 1
    means at least one shape rung compiled once and served multiple
    tenants (engine/compilecache.py)."""
    import time

    import jax

    from ksim_tpu.engine.compilecache import COMPILE_CACHE
    from ksim_tpu.jobs import JobManager
    from ksim_tpu.scenario import (
        ScenarioRunner,
        churn_scenario,
        spec_from_operations,
    )

    _child_setup()
    jax.config.update("jax_enable_x64", False)

    def stream():
        return churn_scenario(
            seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100
        )

    doc = {
        "spec": {
            "simulator": {
                "recordMode": "selection",
                "preemption": True,
                "maxPodsPerPass": 1024,
                "podBucketMin": 128,
                "deviceReplay": True,
            },
            "scenario": spec_from_operations(list(stream())),
        }
    }
    jm = JobManager(workers=workers, queue_limit=n_jobs + 2)
    t0 = time.perf_counter()
    jobs = [jm.submit(doc) for _ in range(n_jobs)]
    finished = jm.join(timeout=CHURN_TIMEOUT - 90)
    wall = time.perf_counter() - t0
    jm.shutdown(timeout=5)
    # Solo baseline AFTER the jobs (it reuses their warm executables —
    # cheap — and keeps the jobs' own compile_cache evidence cold-start).
    solo = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        preemption=True,
    )
    rs = solo.run(stream())
    per_job = []
    job_counts = []
    for j in jobs:
        state, result, err = j.result_view()
        counts = None
        lat = {}
        job_wall = None
        if result:
            counts = [
                result["result"]["podsScheduled"],
                result["result"]["unschedulableAttempts"],
            ]
            job_wall = result["result"]["wallSeconds"]
            lat = result.get("latency", {})
        job_counts.append(counts)
        per_job.append(
            {
                "id": j.id,
                "state": state,
                "error": err,
                "counts": counts,
                "wall_s": job_wall,
                "step_p50_s": lat.get("runner.step", {}).get("p50_seconds"),
                "step_p99_s": lat.get("runner.step", {}).get("p99_seconds"),
                "dispatch_p50_s": lat.get("replay.dispatch", {}).get("p50_seconds"),
                "dispatch_p99_s": lat.get("replay.dispatch", {}).get("p99_seconds"),
            }
        )
    solo_counts = [rs.pods_scheduled, rs.unschedulable_attempts]
    out = {
        "events": n_events,
        "nodes": n_nodes,
        "jobs": n_jobs,
        "workers": workers,
        "all_finished": finished,
        "wall_s": round(wall, 1),
        "jobs_per_min": round(n_jobs / wall * 60, 2) if wall else None,
        "solo_counts": solo_counts,
        "job_counts": job_counts,
        "jobs_match_solo": all(c == solo_counts for c in job_counts),
        "per_job": per_job,
        "compile_cache": COMPILE_CACHE.snapshot(),
        "queue": jm.queue.stats(),
        "platform": jax.devices()[0].platform,
    }
    print(
        f"[churn_jobs {n_events}ev/{n_nodes}n x{n_jobs} jobs/{workers} workers] "
        f"{wall:.1f}s ({out['jobs_per_min']} jobs/min, match_solo="
        f"{out['jobs_match_solo']}, compile_cache shared_rungs="
        f"{out['compile_cache']['shared_rungs']})",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_workers(
    seed: int, n_nodes: int, n_events: int, n_jobs: int, fleet_n: int
) -> dict:
    """Fleet scale-out rung (round 20, ksim_tpu/jobs/fleet.py): the
    same multi-tenant storm — ``n_jobs`` copies of the churn stream
    submitted through a frontdoor-role manager, tenants rotating — run
    twice, once against ONE worker process and once against
    ``fleet_n``, every worker a real subprocess claiming jobs by lease
    from the shared jobs dir (``python -m ksim_tpu.jobs``).
    Evidence the record must carry: per-leg aggregate jobs/min and
    per-job ``runner.step`` p99 under the storm, the fleet-vs-solo
    wall speedup, per-job counts with a ``jobs_match_solo`` flag
    against an in-process solo replay, the per-worker lease
    counters (zero takeovers — nothing dies here; the kill-a-worker
    chaos leg lives in ``make restart-check``), and a timed
    fleet-scope observability scrape per leg (workers publish
    snapshots at ``KSIM_OBS_PUBLISH_S=1``; the leg merges them,
    renders Prometheus text, and round-trips the parser — recording
    ``scrape_ms`` and the aggregate dispatch p99 under the storm,
    docs/observability.md "Fleet observability").  Workers run on the
    CPU backend regardless of the probe: N processes cannot share one
    chip, and the scale-out claim is about horizontal fan-out, not
    accelerator placement.  Each leg shares one ``KSIM_AOT_CACHE`` dir
    across its workers with the speculative rescan armed
    (``KSIM_AOT_PREWARM=2``), so one worker's compile is every
    worker's warm start — the round-20 AOT story under load."""
    import shutil
    import subprocess
    import tempfile
    import time

    import jax

    from ksim_tpu import obs
    from ksim_tpu.jobs import JobManager
    from ksim_tpu.scenario import (
        ScenarioRunner,
        churn_scenario,
        spec_from_operations,
    )
    from tests.helpers import sanitized_cpu_env

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    terminal = {"succeeded", "failed", "cancelled", "interrupted"}

    def stream():
        return churn_scenario(
            seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100
        )

    doc = {
        "spec": {
            "simulator": {
                "preemption": True,
                "maxPodsPerPass": 1024,
                "podBucketMin": 128,
                "deviceReplay": True,
            },
            "scenario": spec_from_operations(list(stream())),
        }
    }
    leg_deadline = max((CHURN_TIMEOUT - 180) / 2, 120)

    def leg(nw: int) -> dict:
        d = tempfile.mkdtemp(prefix=f"bench_workers_{nw}_")
        wenv = sanitized_cpu_env({
            "KSIM_WORKERS_POLL_S": "0.1",
            "KSIM_WORKERS_LEASE_S": "8",
            # Workers publish telemetry snapshots every second so the
            # leg's fleet-scope scrape below sees live worker rows.
            "KSIM_OBS_PUBLISH_S": "1",
            # Small local queues spread the storm across the fleet
            # (a worker at capacity skips claiming — backpressure).
            "KSIM_JOBS_QUEUE": "2",
            "KSIM_JOBS_CHECKPOINT_EVERY": "0",
            # One worker's compile = every worker's warm start: shared
            # per-leg XLA disk cache + speculative AOT rescan.  Per-leg
            # (not per-child) so the 1-worker and fleet legs stay
            # hermetic from each other and the machine-wide cache.
            "KSIM_COMPILE_CACHE": os.path.join(d, "xla"),
            "KSIM_AOT_CACHE": os.path.join(d, "aot"),
            "KSIM_AOT_PREWARM": "2",
            "KSIM_AOT_PREWARM_RESCAN_S": "2",
        })
        procs: list = []
        jm = None
        try:
            for i in range(nw):
                procs.append(subprocess.Popen(
                    [
                        sys.executable, "-m", "ksim_tpu.jobs",
                        "--dir", d, "--worker-id", f"w{i}", "--workers", "1",
                    ],
                    env=wenv, cwd=_REPO, stdout=subprocess.PIPE, text=True,
                ))
            for p in procs:
                line = p.stdout.readline()
                if not line.startswith("READY"):
                    raise RuntimeError(f"fleet worker died at startup: {line!r}")
            jm = JobManager(
                workers=0, queue_limit=n_jobs + 2, jobs_dir=d,
                role="frontdoor", worker_id="fd", lease_s=8.0, poll_s=0.1,
            )
            t0 = time.perf_counter()
            jobs = [jm.submit(doc, tenant=f"t{i % 4}") for i in range(n_jobs)]
            end = time.monotonic() + leg_deadline
            while time.monotonic() < end:
                if all(j.status()["state"] in terminal for j in jobs):
                    break
                time.sleep(0.2)
            wall = time.perf_counter() - t0
            per_job = []
            job_counts = []
            finished = 0
            for j in jobs:
                state, result, err = j.result_view()
                counts = None
                p99 = None
                if result:
                    counts = [
                        result["result"]["podsScheduled"],
                        result["result"]["unschedulableAttempts"],
                    ]
                    lat = result.get("latency", {})
                    # Device-replay jobs time per-segment dispatches,
                    # per-pass jobs time runner.step — either way it is
                    # the per-step latency under the storm.
                    p99 = (
                        lat.get("replay.dispatch")
                        or lat.get("runner.step")
                        or {}
                    ).get("p99_seconds")
                if state == "succeeded":
                    finished += 1
                job_counts.append(counts)
                per_job.append({
                    "id": j.id, "state": state, "error": err,
                    "owner": j.status()["owner"], "counts": counts,
                    "step_p99_s": p99,
                })
            p99s = [pj["step_p99_s"] for pj in per_job if pj["step_p99_s"]]
            counters = jm.snapshot().get("fleet", {}).get("workers", {})
            # Fleet-scope scrape while the workers are still up: merge
            # the published snapshots, render + round-trip the
            # Prometheus exposition, and time the whole pull — the
            # scrape cost a fleet operator pays per poll interval.
            t_scrape = time.perf_counter()
            fleet_doc = obs.merge_fleet_docs(obs.read_fleet_snapshots(d))
            expo = obs.render_prometheus(fleet_doc)
            obs.parse_prometheus(expo)
            scrape_ms = round((time.perf_counter() - t_scrape) * 1e3, 2)
            timings = fleet_doc.get("timings", {})
            agg = (
                timings.get("replay.dispatch")
                or timings.get("runner.step")
                or {}
            )
            return {
                "workers": nw,
                "finished": finished,
                "wall_s": round(wall, 1),
                "jobs_per_min": (
                    round(finished / wall * 60, 2) if wall and finished else None
                ),
                "step_p99_max_s": max(p99s) if p99s else None,
                "job_counts": job_counts,
                "per_job": per_job,
                "lease_counters": counters,
                "takeovers": sum(
                    c.get("takeovers", 0) for c in counters.values()
                ),
                "obs_scrape": {
                    "scrape_ms": scrape_ms,
                    "workers_published": sorted(
                        fleet_doc.get("workers", {})
                    ),
                    "dispatch_p99_s": agg.get("p99_seconds"),
                    "exposition_bytes": len(expo),
                },
            }
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            if jm is not None:
                jm.shutdown()
            shutil.rmtree(d, ignore_errors=True)

    solo_leg = leg(1)
    fleet_leg = leg(fleet_n)
    # Solo baseline for the counts lock, in-process (the legs' counts
    # must all match it regardless of which worker ran which job).
    solo = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        preemption=True,
    )
    rs = solo.run(stream())
    solo_counts = [rs.pods_scheduled, rs.unschedulable_attempts]
    all_counts = solo_leg["job_counts"] + fleet_leg["job_counts"]
    speedup = None
    if solo_leg["wall_s"] and fleet_leg["wall_s"]:
        if solo_leg["finished"] == fleet_leg["finished"] == n_jobs:
            speedup = round(solo_leg["wall_s"] / fleet_leg["wall_s"], 2)
    out = {
        "events": n_events,
        "nodes": n_nodes,
        "jobs": n_jobs,
        "fleet": fleet_n,
        "legs": {"one_worker": solo_leg, "fleet": fleet_leg},
        "fleet_speedup": speedup,
        "solo_counts": solo_counts,
        "jobs_match_solo": bool(all_counts) and all(
            c == solo_counts for c in all_counts
        ),
        "platform": jax.devices()[0].platform,
    }
    print(
        f"[churn_workers {n_events}ev/{n_nodes}n x{n_jobs} jobs] "
        f"1w {solo_leg['wall_s']}s vs {fleet_n}w {fleet_leg['wall_s']}s "
        f"(speedup {speedup}, match_solo={out['jobs_match_solo']}, "
        f"takeovers={fleet_leg['takeovers']})",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_restart(seed: int, n_nodes: int, n_events: int) -> dict:
    """Warm-restart rung (round 15, engine/compilecache.py disk layer):
    one device churn replay in THIS fresh process, with
    time-to-first-scheduled-pod measured by a store watcher thread.
    The parent runs this child TWICE against one shared state dir
    (``KSIM_AOT_CACHE`` + ``KSIM_COMPILE_CACHE`` pointed into it, so
    the machine-wide cache never contaminates the comparison): the
    first run is the cold start (every executable compiles, then
    persists), the second IS the warm restart — its record must carry
    ``compile_cache.disk_hits > 0`` and a smaller first-scheduled
    wall."""
    import threading

    import jax

    from ksim_tpu.engine.compilecache import COMPILE_CACHE
    from ksim_tpu.scenario import ScenarioRunner, churn_scenario

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        preemption=True,
    )
    # Time-to-first-scheduled-pod: churn pods are created unbound and
    # only a scheduler bind gives one a nodeName, so the first non-empty
    # pods_with_node() IS the first placement.  The store is internally
    # locked; polling from a side thread never perturbs the replay.
    first_sched: "list[float | None]" = [None]
    stop = threading.Event()
    t0 = time.perf_counter()

    def _watch_first_bind() -> None:  # ksimlint: thread-role(service-loop)
        while not stop.is_set():
            if runner.store.pods_with_node():
                first_sched[0] = round(time.perf_counter() - t0, 3)
                return
            time.sleep(0.005)

    watcher = threading.Thread(
        target=_watch_first_bind, name="restart-first-sched", daemon=True
    )
    watcher.start()
    res = runner.run(
        churn_scenario(seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100)
    )
    stop.set()
    watcher.join(timeout=1)
    cc = COMPILE_CACHE.snapshot()
    drv = runner.replay_driver
    out = {
        "events": res.events_applied,
        "nodes": n_nodes,
        "wall_s": round(res.wall_seconds, 2),
        "first_scheduled_s": first_sched[0],
        "pods_scheduled": res.pods_scheduled,
        "unschedulable_attempts": res.unschedulable_attempts,
        "device_steps": drv.device_steps if drv else None,
        "fallback_steps": drv.fallback_steps if drv else None,
        "compile_cache": {
            k: cc[k]
            for k in (
                "hits", "misses",
                "disk_hits", "disk_misses", "disk_stores", "disk_evictions",
            )
        },
        "platform": jax.devices()[0].platform,
    }
    print(
        f"[churn_restart {n_events}ev/{n_nodes}n] {res.wall_seconds:.1f}s "
        f"first_sched {first_sched[0]}s "
        f"disk_hits={cc['disk_hits']} disk_stores={cc['disk_stores']}",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_resume(
    seed: int, n_nodes: int, n_events: int, phase: str, state_dir: str,
    out_path: str,
) -> dict:
    """Incremental-resume rung (round 16, docs/jobs.md "Incremental
    resume"): three fresh processes over ONE shared jobs dir.

    ``victim`` submits the churn stream as a checkpointed device-replay
    job, writes its evidence the moment the first segment checkpoint is
    durable, then SIGKILLs itself — a real crash (no shutdown, no
    flush; the journal's torn-tail rule owns whatever was mid-append).
    ``resume`` restarts over the same dir with the resume switch on:
    it must restore the checkpoint and replay ONLY the remaining
    suffix.  ``scratch`` is the control — the same job, fresh in-memory
    plane.  Both report the JOB's replay wall (compile included in
    both, so the delta is the skipped prefix, not cache luck)."""
    import signal as _signal

    import jax

    from ksim_tpu.jobs import JobManager
    from ksim_tpu.scenario import churn_scenario, spec_from_operations

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    doc = {
        "spec": {
            "simulator": {
                "deviceReplay": True,
                "maxPodsPerPass": 1024,
                "podBucketMin": 128,
            },
            "scenario": spec_from_operations(
                list(
                    churn_scenario(
                        seed,
                        n_nodes=n_nodes,
                        n_events=n_events,
                        ops_per_step=100,
                    )
                )
            ),
        }
    }

    def _job_record(job, wall: float) -> dict:
        state, result, err = job.result_view()
        rec: dict = {"job": job.id, "state": state, "error": err,
                     "wall_s": round(wall, 2)}
        if result:
            rec["counts"] = [
                result["result"]["podsScheduled"],
                result["result"]["unschedulableAttempts"],
            ]
            rec["events"] = result["result"]["eventsApplied"]
            rec["job_wall_s"] = result["result"]["wallSeconds"]
            if result.get("resume"):
                rec["resume"] = result["resume"]
                rec["events_replayed"] = result["resume"]["eventsReplayed"]
        return rec

    if phase == "victim":
        jm = JobManager(
            workers=1, queue_limit=4, jobs_dir=state_dir, checkpoint_every=1
        )
        job = jm.submit(doc)
        while True:
            st = job.status()
            if st["checkpoint_segment"] is not None or st["state"] in (
                "succeeded", "failed",
            ):
                break
            time.sleep(0.05)
        out = {
            "phase": "victim",
            "job": job.id,
            "state_at_kill": st["state"],
            "checkpoint_segment": st["checkpoint_segment"],
        }
        out.update(_proc_watermarks())
        print(
            f"[churn_resume victim] checkpoint_segment="
            f"{st['checkpoint_segment']} -> SIGKILL",
            file=sys.stderr,
            flush=True,
        )
        # The JSON must land BEFORE the crash: the parent reads it off
        # disk regardless of our exit signal.
        _write_json(out_path, out)
        os.kill(os.getpid(), _signal.SIGKILL)
        raise AssertionError("unreachable")  # pragma: no cover
    if phase == "resume":
        t0 = time.perf_counter()
        jm = JobManager(
            workers=1, queue_limit=4, jobs_dir=state_dir,
            resume=True, checkpoint_every=0,
        )
        jobs = jm.jobs()
        if len(jobs) != 1:
            return {"error": f"resume found {len(jobs)} journaled jobs"}
        job = jobs[0]
        job.wait_done(CHURN_EXACT_TIMEOUT)
        wall = time.perf_counter() - t0
        jm.shutdown(timeout=5)
        out = {"phase": "resume", **_job_record(job, wall)}
        out["resumed_from"] = job.status()["resumed_from"]
    else:
        t0 = time.perf_counter()
        jm = JobManager(workers=1, queue_limit=4)
        job = jm.submit(doc)
        job.wait_done(CHURN_EXACT_TIMEOUT)
        wall = time.perf_counter() - t0
        jm.shutdown(timeout=5)
        out = {"phase": "scratch", **_job_record(job, wall)}
    out["platform"] = jax.devices()[0].platform
    print(
        f"[churn_resume {phase} {n_events}ev/{n_nodes}n] "
        f"{out.get('state')} in {out.get('wall_s')}s "
        f"counts={out.get('counts')} "
        f"events_replayed={out.get('events_replayed')}",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_trace(
    trace_file: str, fmt: str, nodes: int, ops_per_step: int, max_events: int
) -> dict:
    """Trace-ingestion rung (round 14, ksim_tpu/traces): a REAL cluster
    trace (Borg/Alibaba format; the bundled hand-checked fixture by
    default) compiled to a churn stream and replayed through BOTH the
    per-pass and the device-resident path.  Evidence the record must
    carry: both paths' scheduled/unschedulable counts with a
    ``counts_match`` flag (the second locked-count workload family next
    to synthetic churn — tests/test_behavior_locks.py pins the fixture),
    ``device_step_fraction`` with the fallback histogram (the
    in-vocabulary claim: 0 fallbacks on the device path), the
    ``phases`` wall-clock split, and the ingestion shape (records ->
    ops -> steps)."""
    import jax

    from ksim_tpu.scenario import ScenarioRunner
    from ksim_tpu.traces import trace_operations

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    t0 = time.perf_counter()
    ops = trace_operations(
        trace_file, fmt, nodes=nodes, max_events=max_events,
        seed=0, ops_per_step=ops_per_step,
    )
    ingest_s = time.perf_counter() - t0
    base = ScenarioRunner(pod_bucket_min=64)
    rb = base.run(list(ops))
    dev = ScenarioRunner(pod_bucket_min=64, device_replay=True)
    rd = dev.run(list(ops))
    drv = dev.replay_driver
    base_counts = [rb.pods_scheduled, rb.unschedulable_attempts]
    dev_counts = [rd.pods_scheduled, rd.unschedulable_attempts]
    out = {
        "trace": os.path.basename(trace_file),
        "format": fmt,
        "nodes": nodes,
        "ops": len(ops),
        "ingest_s": round(ingest_s, 3),
        "events": rd.events_applied,
        "steps": len(rd.steps),
        "wall_s": round(rd.wall_seconds, 1),
        "per_pass_wall_s": round(rb.wall_seconds, 1),
        "counts": dev_counts,
        "per_pass_counts": base_counts,
        "counts_match": dev_counts == base_counts,
        "device_step_fraction": (
            round(drv.device_steps / len(rd.steps), 4) if rd.steps else None
        ),
        "fallback_steps": drv.fallback_steps,
        "unsupported": dict(drv.unsupported),
        "platform": jax.devices()[0].platform,
    }
    if rd.phase_seconds:
        out["phases"] = {
            name: {"seconds": rd.phase_seconds[name], "count": rd.phase_counts[name]}
            for name in sorted(rd.phase_seconds)
        }
    print(
        f"[churn_trace {fmt}:{out['trace']} {nodes}n] device {rd.wall_seconds:.1f}s "
        f"counts {dev_counts} match={out['counts_match']} "
        f"device_frac={out['device_step_fraction']}",
        file=sys.stderr,
        flush=True,
    )
    return out


def child_churn_stream(
    seed: int,
    records: int,
    nodes: int,
    ops_per_step: int,
    max_events: int,
    window: int,
    queue_windows: int,
) -> dict:
    """Streaming-ingest rung (round 22, ksim_tpu/traces/stream): a
    synthetic Borg JSONL generated in-child (deterministic from
    ``seed``; SUBMIT/FINISH pairs so every record carries a lifetime)
    is replayed through the windowed streaming pipeline — parse ->
    resample -> compile feeding the device executor window-by-window —
    and then through the materialized path for the byte-identity check.
    Evidence the record must carry: ``rss_after_stream_kb``, the VmHWM
    snapshot taken IMMEDIATELY after the streaming replay and BEFORE
    the materialized comparison (the O(window) peak-memory claim — the
    parent stage ratios it across a 10x stream-growth leg),
    ``events_per_sec`` (events applied over the end-to-end streaming
    wall, ingest included — the headline), the producer stats
    (windows/queue_peak/fallback), and ``counts_match`` between the
    streamed and materialized runs."""
    import random

    import jax

    from ksim_tpu.scenario import ScenarioRunner
    from ksim_tpu.traces import stream_trace_operations, trace_operations

    _child_setup()
    jax.config.update("jax_enable_x64", False)
    rng = random.Random(seed)
    tmp_dir = tempfile.mkdtemp(prefix="bench_stream_")
    try:
        path = os.path.join(tmp_dir, "synthetic_borg.jsonl")
        t_us = 0
        with open(path, "w") as f:
            for i in range(records):
                t_us += rng.randrange(1_000, 50_000)
                # Lifetimes stay SHORT relative to the trace span
                # (records x ~25 ms mean interarrival) so FINISH
                # deletes interleave with arrivals and the LIVE pod
                # population stays bounded: the rung's RSS ratio must
                # measure ingest memory (O(window) vs O(stream)), not
                # cluster-saturation memory from a workload whose pods
                # never complete in-span.
                life_us = rng.randrange(500_000, 60_000_000)
                req = {
                    "cpus": rng.choice((0.01, 0.025, 0.05, 0.1)),
                    "memory": rng.choice((0.005, 0.01, 0.02, 0.05)),
                }
                f.write(json.dumps({
                    "time": t_us, "type": "SUBMIT", "collection_id": i,
                    "instance_index": 0,
                    "priority": rng.choice((0, 103, 117, 200, 360)),
                    "resource_request": req,
                }) + "\n")
                f.write(json.dumps({
                    "time": t_us + life_us, "type": "FINISH",
                    "collection_id": i, "instance_index": 0,
                }) + "\n")
        # The decompressed-byte guard exists for untrusted registry
        # uploads; this child generated the file itself, and the
        # 10x-source leg legitimately exceeds the 64 MiB default.
        os.environ["KSIM_TRACES_MAX_BYTES"] = str(
            os.path.getsize(path) + 1_048_576
        )
        t0 = time.perf_counter()
        stream = stream_trace_operations(
            path, "borg", nodes=nodes, max_events=max_events, seed=seed,
            ops_per_step=ops_per_step, window=window or None,
            queue_windows=queue_windows or None,
        )
        dev = ScenarioRunner(pod_bucket_min=64, device_replay=True)
        rs = dev.run(stream)
        stream_wall = time.perf_counter() - t0
        sstats = stream.stats()
        drv = dev.replay_driver
        # The peak-memory evidence: VmHWM NOW, before the materialized
        # comparison run hoists the whole operation list into memory.
        rss_after_stream_kb = _proc_watermarks().get("rss_peak_kb")
        ops = trace_operations(
            path, "borg", nodes=nodes, max_events=max_events, seed=seed,
            ops_per_step=ops_per_step,
        )
        mat = ScenarioRunner(pod_bucket_min=64, device_replay=True)
        rm = mat.run(list(ops))
        stream_counts = [rs.pods_scheduled, rs.unschedulable_attempts]
        mat_counts = [rm.pods_scheduled, rm.unschedulable_attempts]
        out = {
            "records": records,
            "max_events": max_events,
            "nodes": nodes,
            "window_ops": sstats["window_ops"],
            "queue_windows": sstats["queue_windows"],
            "windows": sstats["windows"],
            "queue_peak": sstats["queue_peak"],
            "ingest_fallback": sstats["fallback"],
            "events": rs.events_applied,
            "steps": len(rs.steps),
            "wall_s": round(stream_wall, 3),
            "events_per_sec": (
                round(rs.events_applied / stream_wall, 1)
                if stream_wall > 0 else None
            ),
            "rss_after_stream_kb": rss_after_stream_kb,
            "ingest_prefetches": (
                drv.stats().get("ingest_prefetches") if drv else None
            ),
            "counts": stream_counts,
            "materialized_counts": mat_counts,
            "counts_match": stream_counts == mat_counts,
            "platform": jax.devices()[0].platform,
        }
    finally:
        shutil.rmtree(tmp_dir, ignore_errors=True)
    print(
        f"[churn_stream {records}rec/{max_events}ev] "
        f"{out['events']} events in {out['wall_s']}s "
        f"({out['events_per_sec']}/s) rss_after_stream={rss_after_stream_kb}kB "
        f"windows={out['windows']} match={out['counts_match']}",
        file=sys.stderr,
        flush=True,
    )
    return out


def _proc_watermarks() -> dict:
    """This process's /proc watermarks (stdlib + procfs only, guarded
    for non-Linux): the memory-map count — XLA:CPU executables each mmap
    code pages, and the kernel's vm.max_map_count=65530 default kills a
    long child at ~63k maps (repo CLAUDE.md) — and the kernel's RSS
    high-water mark (VmHWM).  Maps are sampled at end-of-rung; under
    XLA executable accumulation the count is monotone, so the sample IS
    the rung's peak unless a cache shed ran.  Recording them per rung
    turns the SIGSEGV class from fatal-only into an observable trend."""
    out: dict = {}
    try:
        with open("/proc/self/maps") as f:
            out["maps_count"] = sum(1 for _ in f)
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    out["rss_peak_kb"] = int(line.split()[1])
                    break
    except (OSError, ValueError, IndexError):
        pass
    return out


def _child_main(args: argparse.Namespace) -> None:
    """Entry for --child invocations: run the payload, write its JSON to
    --out (atomic rename), exit 0.  Any exception leaves a JSON error
    record instead, so the parent can distinguish crash kinds.  Every
    record (success or error) carries the child's /proc watermarks."""
    try:
        if args.child == "probe":
            out = child_probe()
        elif args.child == "rung":
            out = child_rung(
                args.pods, args.nodes, args.seed, args.repeats, args.slice_pods
            )
        elif args.child == "churn":
            out = child_churn(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.churn_exact,
                args.churn_device,
                args.churn_preempt,
                args.churn_record_full,
            )
        elif args.child == "churn_shard":
            out = child_churn_shard(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.shard_tp,
            )
        elif args.child == "churn_fleet":
            out = child_churn_fleet(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.fleet_lanes,
            )
        elif args.child == "churn_fleet_shard":
            out = child_churn_fleet_shard(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.fleet_lanes,
                args.shard_tp,
            )
        elif args.child == "churn_jobs":
            out = child_churn_jobs(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.jobs_count,
                args.jobs_workers,
            )
        elif args.child == "churn_workers":
            out = child_churn_workers(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.jobs_count,
                args.workers_fleet,
            )
        elif args.child == "churn_restart":
            out = child_churn_restart(
                args.seed,
                args.churn_nodes,
                args.churn_events,
            )
        elif args.child == "churn_resume":
            out = child_churn_resume(
                args.seed,
                args.churn_nodes,
                args.churn_events,
                args.resume_phase,
                args.state_dir,
                args.out,
            )
        elif args.child == "churn_trace":
            out = child_churn_trace(
                args.trace_file,
                args.trace_format,
                args.trace_nodes,
                args.trace_ops_per_step,
                args.trace_max_events,
            )
        elif args.child == "churn_stream":
            out = child_churn_stream(
                args.seed,
                args.stream_records,
                args.stream_nodes,
                args.stream_ops_per_step,
                args.stream_max_events,
                args.stream_window,
                args.stream_queue,
            )
        else:  # pragma: no cover
            raise ValueError(f"unknown child mode {args.child!r}")
    except BaseException:
        traceback.print_exc(file=sys.stderr)
        out = {"error": traceback.format_exc(limit=1).strip().splitlines()[-1]}
        out.update(_proc_watermarks())
        _write_json(args.out, out)
        sys.exit(1)
    out.update(_proc_watermarks())
    _write_json(args.out, out)


def _write_json(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Parent orchestrator (stdlib only — never imports jax).
# ---------------------------------------------------------------------------


def _sanitized_env() -> dict:
    """CPU-fallback environment: drop the axon TPU sitecustomize from
    PYTHONPATH (it blocks on a dead chip even under JAX_PLATFORMS=cpu) and
    force the CPU backend.  Single source of truth lives in tests.helpers
    (stdlib-only, safe for this jax-free parent)."""
    sys.path.insert(0, _REPO)
    try:
        from tests.helpers import sanitized_cpu_env
    finally:
        sys.path.pop(0)
    return sanitized_cpu_env()


class _Orchestrator:
    def __init__(self, budget_s: float) -> None:
        self.t0 = time.monotonic()
        self.budget_s = budget_s
        self.payload: dict = {
            "metric": "sched_pairs_per_sec",
            "value": 0,
            "unit": (
                "pod-node pairs/s (sequential-commit scan, bit-exact "
                "finalscore mode, largest completed rung)"
            ),
            "vs_baseline": 0.0,
            "platform": None,
            "rungs": {},
        }
        self._emitted = False
        self._child: subprocess.Popen | None = None
        atexit.register(self.emit)
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, self._on_signal)

    # -- lifecycle ---------------------------------------------------------

    def _on_signal(self, signum, _frame) -> None:
        print(f"bench: caught signal {signum}, emitting partial results", file=sys.stderr)
        if self._child is not None and self._child.poll() is None:
            _kill_tree(self._child)
        self.payload.setdefault("interrupted", signal.Signals(signum).name)
        self.emit()
        os._exit(0)

    def remaining(self) -> float:
        return self.budget_s - (time.monotonic() - self.t0) - EMIT_RESERVE

    def emit(self) -> None:
        if self._emitted:
            return
        rungs = self.payload["rungs"]
        headline = 0
        headline_platform = None
        # Sliced rungs (bounded CPU measurements of the big shapes) stay
        # recorded per-rung but only claim the headline when no fully-run
        # rung exists.
        for sliced_ok in (False, True):
            for key, r in rungs.items():
                if key == "churn" or not isinstance(r, dict):
                    continue
                if "sched_pairs_per_sec" not in r:
                    continue
                if bool(r.get("slice_pods")) != sliced_ok:
                    continue
                headline = r["sched_pairs_per_sec"]
                headline_platform = r.get("platform")
            if headline:
                break
        self.payload["value"] = headline
        self.payload["vs_baseline"] = round(headline / 50_000, 2)
        if headline_platform:
            # Attribute the record to the backend that actually produced
            # the headline rung (a mid-run fallback may mix platforms).
            self.payload["platform"] = headline_platform
        # The leading newline terminates any partially-written line if a
        # signal interrupted an in-flight print; the flag flips only AFTER
        # the line is out, so a signal handler re-entering emit() mid-print
        # re-prints a complete line rather than silently losing it.
        sys.stdout.write("\n" + json.dumps(self.payload) + "\n")
        sys.stdout.flush()
        self._emitted = True
        try:
            _write_json(os.path.join(_REPO, "bench_partial.json"), self.payload)
        except OSError:
            pass

    def flush_partial(self) -> None:
        try:
            _write_json(os.path.join(_REPO, "bench_partial.json"), self.payload)
        except OSError:
            pass

    # -- subprocess driver -------------------------------------------------

    def run_child(self, mode: str, extra: list[str], env: dict, timeout: float) -> dict:
        """Run one child payload under a watchdog; returns its JSON result
        or an {"error": ...} record.  Never raises."""
        timeout = min(timeout, max(self.remaining(), 5))
        fd, out_path = tempfile.mkstemp(prefix=f"bench_{mode}_", suffix=".json")
        os.close(fd)
        os.unlink(out_path)
        cmd = [
            sys.executable,
            os.path.abspath(__file__),
            "--child",
            mode,
            "--out",
            out_path,
            *extra,
        ]
        try:
            try:
                self._child = subprocess.Popen(
                    cmd, cwd=_REPO, env=env, start_new_session=True
                )
                try:
                    rc = self._child.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    _kill_tree(self._child)
                    # The child may have finished its write just as the
                    # watchdog fired — a complete result beats a timeout
                    # error record.
                    late = _read_json(out_path)
                    if late is not None:
                        late["late_after_timeout"] = True
                        return late
                    return {"error": f"timeout after {timeout:.0f}s"}
            except OSError as e:
                # fork/spawn failure on a degraded host: record, keep going.
                return {"error": f"spawn failed: {e}"}
            finally:
                self._child = None
            result = _read_json(out_path)
            if result is None:
                return {"error": f"child exited rc={rc} with no result"}
            return result
        finally:
            for p in (out_path, out_path + ".tmp"):
                try:
                    os.unlink(p)
                except OSError:
                    pass


def _kill_tree(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        pass


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--only", type=str, default="", help="pods x nodes, e.g. 10000x5000")
    ap.add_argument("--skip-churn", action="store_true")
    ap.add_argument("--churn-events", type=int, default=50_000)
    ap.add_argument("--churn-nodes", type=int, default=2_000)
    ap.add_argument("--churn-exact", action="store_true")
    ap.add_argument("--churn-device", action="store_true")
    ap.add_argument("--churn-preempt", action="store_true")
    ap.add_argument("--churn-record-full", action="store_true")
    # Fleet width for the churn_fleet rung; KSIM_FLEET steers it through
    # the environment (the stdlib-only parent just forwards the number).
    try:
        default_fleet = int(os.environ.get("KSIM_FLEET", "8"))
    except ValueError:
        default_fleet = 8
    ap.add_argument("--fleet-lanes", type=int, default=default_fleet)
    ap.add_argument("--shard-tp", type=int, default=8)
    # Job-plane rung shape (the stdlib-only parent forwards the numbers;
    # the child reads no environment for them).
    ap.add_argument("--jobs-count", type=int, default=8)
    ap.add_argument("--jobs-workers", type=int, default=4)
    # Fleet scale-out rung: worker PROCESS count for the multi-process
    # leg (the other leg is always one process).
    ap.add_argument("--workers-fleet", type=int, default=4)
    # Trace-rung shape (stdlib parent forwards; the bundled hand-checked
    # fixture is the default — the locked trace workload family).
    ap.add_argument(
        "--trace-file",
        type=str,
        default=os.path.join(_REPO, "tests", "fixtures", "traces", "borg_mini.jsonl"),
    )
    ap.add_argument("--trace-format", type=str, default="borg")
    # Warm-restart rung shape: small on purpose — the rung's claim is
    # about compile-persistence recovery, not stream length, and the
    # child runs twice.
    ap.add_argument("--restart-events", type=int, default=1_000)
    ap.add_argument("--restart-nodes", type=int, default=500)
    # Incremental-resume rung shape: the locked 6k churn prefix by
    # default, so counts_match doubles as a behavior-lock check across
    # the crash (docs/jobs.md "Incremental resume").
    ap.add_argument("--resume-events", type=int, default=6_000)
    ap.add_argument("--resume-nodes", type=int, default=2_000)
    ap.add_argument("--trace-nodes", type=int, default=24)
    ap.add_argument("--trace-ops-per-step", type=int, default=2)
    ap.add_argument("--trace-max-events", type=int, default=0)
    ap.add_argument("--stream-records", type=int, default=30_000)
    ap.add_argument("--stream-max-events", type=int, default=2_500)
    ap.add_argument("--stream-nodes", type=int, default=64)
    ap.add_argument("--stream-ops-per-step", type=int, default=100)
    ap.add_argument("--stream-window", type=int, default=0)
    ap.add_argument("--stream-queue", type=int, default=0)
    try:
        default_budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    except ValueError:
        default_budget = 1500.0
    ap.add_argument(
        "--budget",
        type=float,
        default=default_budget,
        help="wall-clock budget (s); rungs stop in time to emit the JSON line",
    )
    # Internal: subprocess payload modes.
    ap.add_argument(
        "--child",
        choices=[
            "probe", "rung", "churn", "churn_shard", "churn_fleet",
            "churn_fleet_shard", "churn_jobs", "churn_workers",
            "churn_trace", "churn_stream", "churn_restart", "churn_resume",
        ],
        default=None,
    )
    ap.add_argument(
        "--resume-phase", choices=["victim", "resume", "scratch"],
        default="victim",
    )
    ap.add_argument("--state-dir", type=str, default="")
    ap.add_argument("--pods", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=0)
    ap.add_argument("--slice-pods", type=int, default=0)
    ap.add_argument("--out", type=str, default="")
    args = ap.parse_args()

    if args.child:
        _child_main(args)
        return

    orch = _Orchestrator(args.budget)
    payload = orch.payload

    # Backend probe: default env (TPU under the driver) first, CPU-fallback
    # sanitized env second.  Probing runs in subprocesses because jax
    # backend init can block indefinitely on a wedged chip.
    env = dict(os.environ)
    probe = orch.run_child("probe", [], env, PROBE_TIMEOUT)
    fallback = False
    if "error" in probe:
        payload["probe_error"] = probe["error"]
        print(f"bench: default backend probe failed ({probe['error']}); "
              "falling back to CPU", file=sys.stderr)
        env = _sanitized_env()
        probe = orch.run_child("probe", [], env, 60)
        fallback = True
        if "error" in probe:
            payload["error"] = f"no usable backend: {probe['error']}"
            orch.emit()
            return
    payload["platform"] = probe.get("platform")
    # fallback_cpu records CHIP FAILURE (probe failed, sanitized-env
    # retry succeeded) — provenance the round notes rely on.  CPU
    # SIZING additionally applies to an intentionally-CPU environment
    # (JAX_PLATFORMS=cpu: the probe then SUCCEEDS on cpu and previously
    # took the TPU-sized ladder into a guaranteed 10kx5k timeout);
    # that case is recorded as cpu_sized without the failure flag.
    payload["fallback_cpu"] = fallback
    if probe.get("platform") == "cpu":
        fallback = True  # local sizing flag from here on
    payload["cpu_sized"] = fallback
    print(f"bench: backend={probe.get('platform')} "
          f"devices={probe.get('device_count')} fallback={fallback}",
          file=sys.stderr)

    ladder = CPU_LADDER if fallback else LADDER
    if args.only:
        p, n = args.only.lower().split("x")
        ladder = [(int(p), int(n))]

    common = ["--seed", str(args.seed), "--repeats", str(args.repeats)]

    def check_mid_run_fallback() -> str:
        """After a failed stage on the default (TPU) backend, re-probe it;
        a chip that died MID-run (the BENCH_r01 kernel-fault mode) would
        otherwise burn every later stage's full timeout.  On a dead
        re-probe the remaining stages switch to the sanitized CPU
        environment so a recorded number still exists.

        Returns "transitioned" on that fresh TPU->CPU switch (the
        caller's cue to retry the failed stage once on CPU), "alive"
        when the re-probe CONFIRMED the backend is healthy (the caller
        may treat the failure as transient), and "unprobed" when no
        probe ran (already on fallback, or not enough budget for a
        meaningful probe — backend init can take up to PROBE_TIMEOUT,
        and a clamped 5s probe would declare a healthy chip dead)."""
        nonlocal env, fallback
        if fallback or orch.remaining() < 75:
            return "unprobed"
        reprobe = orch.run_child("probe", [], env, 60)
        if "error" not in reprobe:
            return "alive"
        print("bench: default backend died mid-run; switching remaining "
              "stages to CPU", file=sys.stderr)
        payload["mid_run_fallback"] = reprobe["error"]
        env = _sanitized_env()
        fallback = True
        payload["fallback_cpu"] = True
        payload["cpu_sized"] = True
        return "transitioned"

    def retry_transient(probe_state: str, result: dict, rerun, label: str) -> dict:
        """One same-env retry for a stage that died on a CONFIRMED-alive
        backend: the axon relay is known to drop a remote_compile
        mid-flight (observed: the 10kx5k rung died exactly this way
        while the very next standalone run recorded 25M pairs/s).
        Retries ONLY when the re-probe actually ran and said alive —
        never against a wedged or unprobed tunnel — and never for
        timeouts (a too-slow shape stays too slow and would just burn
        another stage cap)."""
        if probe_state != "alive" or "timeout" in result.get("error", ""):
            return result
        if orch.remaining() < 60:
            return result
        print(
            f"bench: {label} failed transiently on a live backend; "
            "retrying once",
            file=sys.stderr,
        )
        retry = rerun()
        return retry if "error" not in retry else result

    def run_rung_stage(n_pods: int, n_nodes: int, slice_pods: int = 0) -> None:
        key = f"{n_pods}x{n_nodes}"
        cap = CPU_RUNG_TIMEOUT if fallback else RUNG_TIMEOUT.get(key, 600)
        if orch.remaining() < 30:
            payload["rungs"][key] = {"error": "skipped: budget exhausted"}
            return
        if fallback and not slice_pods and (n_pods, n_nodes) not in CPU_LADDER:
            # Already on CPU with a TPU-sized shape: the full run is a
            # guaranteed timeout — go straight to the bounded measurement
            # instead of burning the stage cap first.
            slice_pods = CPU_SLICE_PODS
        extra = ["--pods", str(n_pods), "--nodes", str(n_nodes), *common]
        if slice_pods:
            extra += ["--slice-pods", str(slice_pods)]
        result = orch.run_child("rung", extra, env, cap)
        if "error" in result:
            state = check_mid_run_fallback()
            if state == "transitioned":
                # Fresh transition only: retry once in the sanitized env —
                # CPU-sized rungs as-is, bigger shapes sliced (a run that
                # was ALWAYS on CPU gains nothing from an identical retry).
                retry_extra = list(extra)
                if (n_pods, n_nodes) not in CPU_LADDER and not slice_pods:
                    retry_extra += ["--slice-pods", str(CPU_SLICE_PODS)]
                retry = orch.run_child("rung", retry_extra, env, CPU_RUNG_TIMEOUT)
                result = retry if "error" not in retry else result
            else:
                result = retry_transient(
                    state,
                    result,
                    lambda: orch.run_child("rung", extra, env, cap),
                    f"rung {key}",
                )
        payload["rungs"][key] = result
        orch.flush_partial()

    def run_churn_stage() -> None:
        if args.skip_churn or args.only:
            return
        churn_events = args.churn_events
        churn_nodes = args.churn_nodes
        if fallback:
            # CPU can't chew the full 50k inside the budget, but the
            # optimized host path replays CPU_CHURN_CAP events in well
            # under the stage cap — a real dynamic-state record.
            churn_events = min(churn_events, CPU_CHURN_CAP[0])
            churn_nodes = min(churn_nodes, CPU_CHURN_CAP[1])
        if orch.remaining() < 60:
            payload["rungs"]["churn"] = {"error": "skipped: budget exhausted"}
            return

        def launch(events: int, nodes: int) -> dict:
            extra = [
                "--seed", str(args.seed),
                "--churn-events", str(events),
                "--churn-nodes", str(nodes),
            ]
            # --churn-exact on the CLI runs the MAIN replay in x64 exact
            # mode (slow: x64 emulation compounds ~10x over ~500 passes).
            if args.churn_exact:
                extra.append("--churn-exact")
            return orch.run_child("churn", extra, env, CHURN_TIMEOUT)

        result = launch(churn_events, churn_nodes)
        if "error" in result:
            state = check_mid_run_fallback()
            if state == "transitioned":
                # Chip died during churn: one CPU retry at the same
                # reduced size the planned-fallback path uses, so the
                # config-5 record exists.
                retry = launch(
                    min(churn_events, CPU_CHURN_CAP[0]),
                    min(churn_nodes, CPU_CHURN_CAP[1]),
                )
                result = retry if "error" not in retry else result
            else:
                result = retry_transient(
                    state,
                    result,
                    lambda: launch(churn_events, churn_nodes),
                    "churn",
                )
        payload["rungs"]["churn"] = result
        orch.flush_partial()

    def run_secondary_churn_rung(
        rung_name: str,
        child_args,
        timeout: float,
        min_budget: float = 90,
        mode: str = "churn",
    ) -> None:
        """Shared scaffolding of the secondary churn rungs: the budget
        guard, the child launch, and the mid-run-fallback protocol (a
        chip that died mid-run gets ONE resized retry; a transient relay
        drop on a confirmed-alive backend gets the one-shot
        retry_transient) — one copy, three rungs.  ``child_args(resized)``
        builds the child argv; ``resized=True`` after a mid-run chip
        transition (the rung should re-cap to its CPU sizing)."""
        if args.skip_churn or args.only:
            return
        if orch.remaining() < min_budget:
            payload["rungs"][rung_name] = {"error": "skipped: budget exhausted"}
            return

        def launch(resized: bool) -> dict:
            return orch.run_child(mode, child_args(resized), env, timeout)

        result = launch(fallback)
        if "error" in result:
            state = check_mid_run_fallback()
            if state == "transitioned":
                retry = launch(True)
                result = retry if "error" not in retry else result
            else:
                result = retry_transient(
                    state, result, lambda: launch(fallback), rung_name
                )
        payload["rungs"][rung_name] = result
        orch.flush_partial()

    def churn_device_args(resized: bool, extra: "list[str]" = ()) -> list:
        """Device-rung child argv.  On CPU (or after a mid-run chip
        death) cap to the 6k prefix: counts and the dispatch ratio are
        platform-independent, and the device path's padded universe
        makes the full 50k replay CPU-hostile.  Preemption ON since
        round 7: a no-op for this stream's outcomes (no priority
        strata), but it exercises the on-device victim search's
        no-candidate path and proves the former blanket "preemption"
        fallback (PR 1: every step rejected) is gone — the locked
        counts must hold unchanged."""
        events, nodes = args.churn_events, args.churn_nodes
        if resized:
            events = min(events, 6_000)
            nodes = min(nodes, CPU_CHURN_CAP[1])
        return [
            "--seed", str(args.seed),
            "--churn-events", str(events),
            "--churn-nodes", str(nodes),
            "--churn-device",
            "--churn-preempt",
            *extra,
        ]

    def run_churn_device_stage() -> None:
        """Device-resident replay rung (engine/replay.py): the K-step
        segment-scan path over the same churn stream.  Evidence it must
        record: byte-identical counts through the device path, and the
        per-replay dispatch reduction vs one round trip per pass (the
        round-5 TPU latency floor this path exists to remove)."""
        run_secondary_churn_rung(
            "churn_device", churn_device_args, CHURN_TIMEOUT
        )

    def run_churn_device_full_stage() -> None:
        """Bounded record="full" device rung (6k prefix): evidence that
        full-record segments stream their result tensors out of the
        segment scan instead of falling back per-pass (the other
        round-7 fallback-class removal), with the locked prefix counts
        and the fallback histogram in the record.  Bounded: full-record
        annotation decode is O(N) per attempt by design — the 50k run
        is a product workload, not a bench rung."""
        run_secondary_churn_rung(
            "churn_device_full",
            lambda resized: churn_device_args(True, ["--churn-record-full"]),
            CHURN_TIMEOUT,
        )

    def run_churn_shard_stage() -> None:
        """Sharded device replay rung (round 17): tp=1 vs tp=8 over the
        6k prefix in one child — counts_match/device_steps_match, zero
        shard_mesh fallbacks, the per-shard full-record byte budget,
        and the per-chip memory watermark next to the phases split.
        Always the 6k prefix: the rung runs the stream twice and the
        sharding claims are about layout, not stream length."""
        run_secondary_churn_rung(
            "churn_shard",
            lambda resized: [
                "--seed", str(args.seed),
                "--churn-events", str(min(args.churn_events, 6_000)),
                "--churn-nodes", str(min(args.churn_nodes, CPU_CHURN_CAP[1])),
                "--shard-tp", str(args.shard_tp),
            ],
            CHURN_TIMEOUT,
            min_budget=120,
            mode="churn_shard",
        )

    def run_churn_fleet_stage() -> None:
        """Fleet replay rung (round 12, engine/fleet.py): S independent
        trajectories of the 6k prefix at 2k nodes through one vmapped
        dispatch per window, next to the SOLO device replay of the same
        stream — the record carries trajectories/sec, the aggregate
        speedup vs running the lanes solo (>= 3x at S=8 is the target),
        per-lane counts (all must match solo), the lanes-on-device
        fraction, and the cohort leader's lowered-once evidence.  Always
        the 6k prefix: the rung runs lanes+1 trajectories' worth of
        device compute, and the fleet claims are about amortization, not
        stream length."""
        run_secondary_churn_rung(
            "churn_fleet",
            lambda resized: [
                "--seed", str(args.seed),
                "--churn-events", str(min(args.churn_events, 6_000)),
                "--churn-nodes", str(min(args.churn_nodes, CPU_CHURN_CAP[1])),
                "--fleet-lanes", str(args.fleet_lanes),
            ],
            CHURN_TIMEOUT,
            min_budget=120,
            mode="churn_fleet",
        )

    def run_churn_fleet_shard_stage() -> None:
        """2-D mesh fleet rung (round 19): 2 lanes over dp composed
        with tp=4 node sharding — the (2, 4) grid that exactly fills
        the 8-device floor every host in the ladder can fake — against
        the solo unsharded device replay of the same 6k prefix.  The
        record carries the aggregate speedup, per-lane counts_match,
        the grids built, per-shard bytes and the leader's dev_const
        counters (the zero-resharding claim).  Always the 6k prefix:
        the claims are about layout and amortization, not stream
        length."""
        run_secondary_churn_rung(
            "churn_fleet_shard",
            lambda resized: [
                "--seed", str(args.seed),
                "--churn-events", str(min(args.churn_events, 6_000)),
                "--churn-nodes", str(min(args.churn_nodes, CPU_CHURN_CAP[1])),
                "--fleet-lanes", "2",
                "--shard-tp", "4",
            ],
            CHURN_TIMEOUT,
            min_budget=120,
            mode="churn_fleet_shard",
        )

    def run_churn_jobs_stage() -> None:
        """Job-plane rung (round 13, ksim_tpu/jobs): 8 concurrent 6k
        churn streams as tenant jobs through the bounded queue on a
        4-worker pool — sustained jobs/min, per-job p50/p99 from each
        job's PRIVATE trace plane, per-job counts + jobs_match_solo,
        and the process-wide compile_cache counters proving same-rung
        tenants compile once (shared_rungs >= 1).  Always the 6k
        prefix: the rung runs jobs+1 trajectories' worth of compute and
        the service claims are about concurrency, not stream length."""
        run_secondary_churn_rung(
            "churn_jobs",
            lambda resized: [
                "--seed", str(args.seed),
                "--churn-events", str(min(args.churn_events, 6_000)),
                "--churn-nodes", str(min(args.churn_nodes, CPU_CHURN_CAP[1])),
                "--jobs-count", str(args.jobs_count),
                "--jobs-workers", str(args.jobs_workers),
            ],
            CHURN_TIMEOUT,
            min_budget=120,
            mode="churn_jobs",
        )

    def run_churn_workers_stage() -> None:
        """Fleet scale-out rung (round 20, ksim_tpu/jobs/fleet.py): a
        4-job multi-tenant storm against 1 vs N lease-claiming worker
        PROCESSES over one shared jobs dir behind a frontdoor-role
        manager — aggregate jobs/min and per-job step p99 per leg, the
        fleet speedup, jobs_match_solo, and the per-worker lease
        counters.  Always the 6k prefix and a 4-job storm: the claim
        is about horizontal process fan-out, not stream length, and
        the rung already runs 2x the storm plus a solo baseline."""
        run_secondary_churn_rung(
            "churn_workers",
            lambda resized: [
                "--seed", str(args.seed),
                "--churn-events", str(min(args.churn_events, 6_000)),
                "--churn-nodes", str(min(args.churn_nodes, CPU_CHURN_CAP[1])),
                "--jobs-count", str(min(args.jobs_count, 4)),
                "--workers-fleet", str(args.workers_fleet),
            ],
            CHURN_TIMEOUT,
            min_budget=180,
            mode="churn_workers",
        )

    def run_churn_trace_stage() -> None:
        """Trace-ingestion rung (round 14, ksim_tpu/traces): the bundled
        hand-checked Borg fixture compiled to a churn stream, replayed
        per-pass AND device-resident — the record carries both counts
        (counts_match), device_step_fraction with the fallback
        histogram, the phases split, and the ingestion shape.  Small by
        construction (the fixture is the locked workload family, not a
        load test), so it shares the secondary-rung scaffolding with a
        modest budget floor."""
        run_secondary_churn_rung(
            "churn_trace",
            lambda resized: [
                "--trace-file", args.trace_file,
                "--trace-format", args.trace_format,
                "--trace-nodes", str(args.trace_nodes),
                "--trace-ops-per-step", str(args.trace_ops_per_step),
                "--trace-max-events", str(args.trace_max_events),
            ],
            CHURN_EXACT_TIMEOUT,
            min_budget=90,
            mode="churn_trace",
        )

    def run_churn_stream_stage() -> None:
        """Streaming-ingest rung (round 22): the SAME streaming child at
        three sizings, each leg a fresh child snapshotting its RSS
        high-water mark right after the streaming replay.  ``cold`` is
        the base sizing; ``large_source`` grows the RAW stream 10x at
        the SAME resample budget — the replayed schedule stays
        budget-sized, so the leg isolates INGEST memory and ``rss_ratio``
        (large_source over cold, acceptance bound <= 1.3) is the
        O(window + budget) peak-memory claim (a materializing ingest
        would hold 10x the parsed records); ``large_budget`` grows the
        resample budget 10x instead for the ``events_per_sec``
        headline under sustained ingest ∥ replay overlap (its RSS is
        NOT the memory claim: replaying 10x the events legitimately
        grows live-cluster state and compiled shapes).  A combined
        ``counts_match`` pins streamed == materialized on all legs."""
        if args.skip_churn or args.only:
            return
        if orch.remaining() < 200:
            payload["rungs"]["churn_stream"] = {"error": "skipped: budget exhausted"}
            return

        def leg_args(records: int, max_events: int) -> list:
            return [
                "--seed", str(args.seed),
                "--stream-records", str(records),
                "--stream-max-events", str(max_events),
                "--stream-nodes", str(args.stream_nodes),
                "--stream-ops-per-step", str(args.stream_ops_per_step),
                "--stream-window", str(args.stream_window),
                "--stream-queue", str(args.stream_queue),
            ]

        cold = orch.run_child(
            "churn_stream",
            leg_args(args.stream_records, args.stream_max_events),
            env,
            CHURN_TIMEOUT,
        )
        record: dict = {"cold": cold}
        match = bool(cold.get("counts_match"))
        if "error" not in cold and orch.remaining() > 150:
            src = orch.run_child(
                "churn_stream",
                leg_args(args.stream_records * 10, args.stream_max_events),
                env,
                CHURN_TIMEOUT,
            )
            record["large_source"] = src
            if "error" not in src:
                ck = cold.get("rss_after_stream_kb")
                lk = src.get("rss_after_stream_kb")
                if ck and lk:
                    record["rss_ratio"] = round(lk / ck, 3)
                match = match and bool(src.get("counts_match"))
        if "error" not in cold and orch.remaining() > 120:
            big = orch.run_child(
                "churn_stream",
                leg_args(args.stream_records, args.stream_max_events * 10),
                env,
                CHURN_TIMEOUT,
            )
            record["large_budget"] = big
            if "error" not in big:
                record["events_per_sec"] = big.get("events_per_sec")
                match = match and bool(big.get("counts_match"))
        record["counts_match"] = match
        payload["rungs"]["churn_stream"] = record
        orch.flush_partial()

    def run_churn_restart_stage() -> None:
        """Warm-restart rung (round 15): the SAME restart child twice
        over one shared persistent-executable dir — cold (empty dir:
        every program compiles and persists) then warm (a FRESH process
        that load-or-compiles from disk).  The record carries both
        walls, both time-to-first-scheduled-pod marks, the warm child's
        compile_cache disk hits/misses, and the derived speedups — the
        restart-recovery claim (docs/jobs.md "Durability & recovery")
        as bench evidence.  The state dir is a throwaway temp dir:
        hermetic from the machine-wide jax cache in both directions."""
        if args.skip_churn or args.only:
            return
        if orch.remaining() < 120:
            payload["rungs"]["churn_restart"] = {"error": "skipped: budget exhausted"}
            return
        state_dir = tempfile.mkdtemp(prefix="bench_restart_")
        renv = dict(env)
        renv["KSIM_AOT_CACHE"] = os.path.join(state_dir, "aot")
        renv["KSIM_COMPILE_CACHE"] = os.path.join(state_dir, "xla")
        extra = [
            "--seed", str(args.seed),
            "--churn-events", str(args.restart_events),
            "--churn-nodes", str(args.restart_nodes),
        ]
        try:
            cold = orch.run_child("churn_restart", extra, renv, CHURN_EXACT_TIMEOUT)
            record: dict = {"cold": cold}
            if "error" not in cold and orch.remaining() > 30:
                warm = orch.run_child(
                    "churn_restart", extra, renv, CHURN_EXACT_TIMEOUT
                )
                record["warm"] = warm
                if "error" not in warm:
                    cw, ww = cold.get("wall_s"), warm.get("wall_s")
                    if cw and ww:
                        record["warm_speedup"] = round(cw / ww, 2)
                    cf = cold.get("first_scheduled_s")
                    wf = warm.get("first_scheduled_s")
                    if cf and wf:
                        record["first_scheduled_speedup"] = round(cf / wf, 2)
                    record["counts_match"] = (
                        cold.get("pods_scheduled"),
                        cold.get("unschedulable_attempts"),
                    ) == (
                        warm.get("pods_scheduled"),
                        warm.get("unschedulable_attempts"),
                    )
            payload["rungs"]["churn_restart"] = record
        finally:
            shutil.rmtree(state_dir, ignore_errors=True)
        orch.flush_partial()

    def run_churn_resume_stage() -> None:
        """Incremental-resume rung (round 16): victim (crashes after
        its first durable checkpoint) -> resume (suffix-only replay
        over the same jobs dir) -> scratch (the control).  The record
        carries both walls, the events replayed vs the total, and a
        ``counts_match`` flag — the crash-safe byte-identical-restore
        claim (docs/jobs.md "Incremental resume") as bench evidence."""
        if args.skip_churn or args.only:
            return
        if orch.remaining() < 180:
            payload["rungs"]["churn_resume"] = {
                "error": "skipped: budget exhausted"
            }
            return
        state_dir = tempfile.mkdtemp(prefix="bench_resume_")
        extra = [
            "--seed", str(args.seed),
            "--churn-events", str(args.resume_events),
            "--churn-nodes", str(args.resume_nodes),
            "--state-dir", state_dir,
        ]
        try:
            victim = orch.run_child(
                "churn_resume", extra + ["--resume-phase", "victim"],
                env, CHURN_EXACT_TIMEOUT,
            )
            record: dict = {"victim": victim}
            if (
                "error" not in victim
                and victim.get("checkpoint_segment") is not None
                and orch.remaining() > 90
            ):
                resume = orch.run_child(
                    "churn_resume", extra + ["--resume-phase", "resume"],
                    env, CHURN_EXACT_TIMEOUT,
                )
                record["resume"] = resume
                scratch = orch.run_child(
                    "churn_resume", extra + ["--resume-phase", "scratch"],
                    env, CHURN_EXACT_TIMEOUT,
                )
                record["scratch"] = scratch
                if "error" not in resume and "error" not in scratch:
                    rw, sw = resume.get("wall_s"), scratch.get("wall_s")
                    if rw and sw:
                        record["resume_speedup"] = round(sw / rw, 2)
                    record["counts_match"] = (
                        resume.get("counts") is not None
                        and resume.get("counts") == scratch.get("counts")
                    )
                    record["events_replayed"] = resume.get("events_replayed")
                    record["total_events"] = scratch.get("events")
        finally:
            shutil.rmtree(state_dir, ignore_errors=True)
        payload["rungs"]["churn_resume"] = record
        orch.flush_partial()

    def run_churn_exact_stage() -> None:
        """Bounded exact-mode (x64) churn: demonstrates in the driver
        record that the replay counts are mode- and platform-identical
        (the round-4 gap — BENCH_r04's f32 TPU churn silently recorded
        counts off the behavior lock).  6k events reproduce the locked
        prefix (2524/471) in ~30 s CPU / ~90 s TPU."""
        main = payload["rungs"].get("churn") or {}
        if main.get("exact"):
            return  # the main churn rung already ran (and recorded) exact
        # NOTE: --churn-exact at the default 50k events will usually
        # TIME OUT (x64 emulation compounds ~10x over ~500 passes vs
        # CHURN_TIMEOUT) — in that case the main rung holds an error
        # record and this bounded stage still supplies exact counts.
        run_secondary_churn_rung(
            "churn_exact_6k",
            lambda resized: [
                "--seed", str(args.seed),
                "--churn-events", "6000",
                "--churn-nodes", str(min(args.churn_nodes, CPU_CHURN_CAP[1])),
                "--churn-exact",
            ],
            CHURN_EXACT_TIMEOUT,
            min_budget=120,
        )

    # Stage order is a record-priority decision: the smallest rung first
    # (a headline number exists early), then the churn replay (config 5's
    # wall-clock target is a first-class result — it must not be the
    # stage a tight budget squeezes out), then the larger rungs that lift
    # the headline.
    if ladder:
        run_rung_stage(*ladder[0])
    run_churn_stage()
    for n_pods, n_nodes in ladder[1:]:
        run_rung_stage(n_pods, n_nodes)
    # Secondary evidence rungs, deliberately AFTER the headline ladder:
    # a wedged child here must not starve the 10kx5k rung's budget.
    run_churn_device_stage()
    run_churn_device_full_stage()
    run_churn_shard_stage()
    run_churn_fleet_stage()
    run_churn_fleet_shard_stage()
    run_churn_jobs_stage()
    run_churn_workers_stage()
    run_churn_trace_stage()
    run_churn_stream_stage()
    run_churn_restart_stage()
    run_churn_resume_stage()
    run_churn_exact_stage()
    if fallback:
        # The north-star shape still gets a measured record on CPU: the
        # full cluster, timing bounded to a CPU_SLICE_PODS slice of the
        # scan + batch paths (round-3 verdict item 2: "bound the
        # measurement, not the rung").  An error entry (a TPU attempt
        # that died before the mid-run fallback, or its failed retry)
        # does NOT satisfy the record — only a measured one does.
        for n_pods, n_nodes in LADDER:
            key = f"{n_pods}x{n_nodes}"
            have = payload["rungs"].get(key)
            if have is None or (
                isinstance(have, dict)
                and "sched_pairs_per_sec" not in have
            ):
                run_rung_stage(n_pods, n_nodes, slice_pods=CPU_SLICE_PODS)

    orch.emit()


if __name__ == "__main__":
    main()
