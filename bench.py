"""Benchmark ladder: pod-node pairs scored per second (BASELINE.md configs).

Runs the full sequential-commit scheduling scan (every pod x node pair
filtered AND scored by every enabled plugin, with capacity/topology commit
between pods) and the one-shot record="full" batch evaluation (the
product's recorded-results path), on whatever jax default backend is live
(TPU under the driver), over a ladder of cluster sizes ending at the
BASELINE config-4 shape (10k pods x 5k nodes).

The headline runs in EXACT mode — x64 enabled, so the int64/float64
scoring paths are active and final scores are bit-exact vs the upstream
plugins (XLA emulates s64/f64 on TPU; verified by
tests/tpu_parity_main.py on a real v5e).  Each rung also reports the
float32 fast mode (documented ±1 rounding tolerance at integer-ratio
boundaries) as ``sched_pairs_per_sec_f32``.

Each rung is isolated: a crash at one size still reports the others.
Prints ONE JSON line with the headline metric (exact sequential-scan
pairs/sec at the largest completed rung):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N/50000, "rungs": {...}}
Baseline: >= 50k pairs/sec north star (BASELINE.json).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import traceback

LADDER = [(1_000, 200), (5_000, 1_000), (10_000, 5_000)]


def run_rung(n_pods: int, n_nodes: int, seed: int, repeats: int) -> dict:
    import jax

    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer
    from tests.helpers import random_cluster

    t0 = time.perf_counter()
    nodes, pods = random_cluster(seed, n_nodes=n_nodes, n_pods=n_pods, bound_fraction=0.0)
    t1 = time.perf_counter()
    feats = Featurizer().featurize(nodes, pods)
    t2 = time.perf_counter()
    print(
        f"[{n_pods}x{n_nodes}] gen {t1-t0:.1f}s featurize {t2-t1:.1f}s; padded "
        f"P={feats.pods.valid.shape[0]} N={feats.nodes.padded} "
        f"on {jax.devices()[0].platform}",
        file=sys.stderr,
    )
    pairs = n_pods * n_nodes

    # Sequential-commit scan (the real scheduling semantics), exact mode
    # (x64 active, set by main) — headline.
    eng = Engine(feats, default_plugins(feats), record="selection")
    eng.schedule()  # compile + warmup
    times = []
    for _ in range(repeats):
        t = time.perf_counter()
        res, _state = eng.schedule(pull_state=False)
        times.append(time.perf_counter() - t)
    sched_s = min(times)

    # float32 fast mode (same kernels, f32 normalize/score paths).
    jax.config.update("jax_enable_x64", False)
    try:
        eng32 = Engine(feats, default_plugins(feats), record="selection")
        eng32.schedule()
        times = []
        for _ in range(repeats):
            t = time.perf_counter()
            eng32.schedule(pull_state=False)
            times.append(time.perf_counter() - t)
        sched32_s = min(times)
    finally:
        jax.config.update("jax_enable_x64", True)

    # One-shot batch evaluation, record="full": materializes every filter
    # reason / raw score / final score matrix (the product's recorded
    # results) on device, streamed chunk by chunk, pulling each chunk's
    # selection decisions to the host (the dense result tensors stay
    # device-resident for on-demand decode — transferring all ~9GB at
    # this shape is not part of the eval path).
    import numpy as np

    engb = Engine(feats, default_plugins(feats), record="full")

    def batch_pass():
        for _s, out in engb.evaluate_batch_chunks():
            np.asarray(out["selected"])
            jax.block_until_ready(out)

    batch_pass()  # compile + warmup
    times = []
    for _ in range(repeats):
        t = time.perf_counter()
        batch_pass()
        times.append(time.perf_counter() - t)
    batch_s = min(times)

    n_sched = int((res.selected >= 0).sum())
    rung = {
        "sched_pairs_per_sec": round(pairs / sched_s),
        "sched_pairs_per_sec_f32": round(pairs / sched32_s),
        "batch_pairs_per_sec": round(pairs / batch_s),
        "sched_s": round(sched_s, 3),
        "sched_f32_s": round(sched32_s, 3),
        "batch_s": round(batch_s, 3),
        "pods_scheduled": n_sched,
        "exact": True,
    }
    print(
        f"[{n_pods}x{n_nodes}] scan-exact {sched_s*1e3:.0f}ms "
        f"({pairs/sched_s/1e6:.2f}M pairs/s, {n_sched} placed), "
        f"scan-f32 {sched32_s*1e3:.0f}ms ({pairs/sched32_s/1e6:.2f}M pairs/s), "
        f"batch-full {batch_s*1e3:.0f}ms ({pairs/batch_s/1e6:.2f}M pairs/s)",
        file=sys.stderr,
    )
    return rung


def run_churn(seed: int, n_nodes: int = 2_000, n_events: int = 50_000) -> dict:
    """BASELINE config 5: churn replay — rolling pod arrivals/completions
    + node drain/replace over the full default plugin set, sequential
    scheduling semantics per step.  Runs in float32 fast mode: this rung
    measures end-to-end wall-clock over 500 scheduling passes, where the
    x64-emulation overhead compounds ~10x (48 vs ~500 ev/s measured) —
    score exactness is covered by the ladder rungs and the TPU parity
    tier."""
    import jax

    from ksim_tpu.scenario import ScenarioRunner, churn_scenario

    jax.config.update("jax_enable_x64", False)
    try:
        # Cap the per-pass pod batch and coarsen the pod bucket: the
        # pending pool under saturation otherwise wanders through every
        # power-of-two bucket up to 16384, and each new shape is another
        # multi-second XLA compile (upstream schedules one pod per cycle;
        # capping a batch just leaves the rest queued).
        runner = ScenarioRunner(max_pods_per_pass=1024, pod_bucket_min=128)
        res = runner.run(
            churn_scenario(seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=100)
        )
    finally:
        jax.config.update("jax_enable_x64", True)
    out = {
        "events": res.events_applied,
        "wall_s": round(res.wall_seconds, 1),
        "events_per_sec": round(res.events_per_second),
        "pods_scheduled": res.pods_scheduled,
        "unschedulable_attempts": res.unschedulable_attempts,
        "steps": len(res.steps),
    }
    print(
        f"[churn {n_events}ev/{n_nodes}n] {res.wall_seconds:.1f}s "
        f"({res.events_per_second:.0f} ev/s, {res.pods_scheduled} scheduled)",
        file=sys.stderr,
    )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--only", type=str, default="", help="pods x nodes, e.g. 10000x5000")
    ap.add_argument("--skip-churn", action="store_true")
    ap.add_argument("--churn-events", type=int, default=50_000)
    args = ap.parse_args()

    import jax

    from ksim_tpu.util import enable_compilation_cache

    # One-time-per-machine XLA compiles (the large-shape scan programs
    # cost 5-60s each to build; the bench is otherwise compile-dominated).
    enable_compilation_cache()
    # Exact mode for the headline: int64/float64 scoring paths active.
    jax.config.update("jax_enable_x64", True)

    ladder = LADDER
    if args.only:
        p, n = args.only.lower().split("x")
        ladder = [(int(p), int(n))]

    rungs: dict[str, dict] = {}
    headline = None
    for n_pods, n_nodes in ladder:
        key = f"{n_pods}x{n_nodes}"
        try:
            rungs[key] = run_rung(n_pods, n_nodes, args.seed, args.repeats)
            headline = rungs[key]["sched_pairs_per_sec"]
        except Exception:
            traceback.print_exc(file=sys.stderr)
            rungs[key] = {"error": traceback.format_exc(limit=1).strip().splitlines()[-1]}

    if not args.skip_churn and not args.only:
        try:
            rungs["churn"] = run_churn(args.seed, n_events=args.churn_events)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            rungs["churn"] = {"error": traceback.format_exc(limit=1).strip().splitlines()[-1]}

    value = headline or 0
    print(
        json.dumps(
            {
                "metric": "sched_pairs_per_sec",
                "value": value,
                "unit": (
                    "pod-node pairs/s (sequential-commit scan, bit-exact "
                    "finalscore mode, largest completed rung)"
                ),
                "vs_baseline": round(value / 50_000, 2),
                "platform": jax.devices()[0].platform,
                "rungs": rungs,
            }
        )
    )


if __name__ == "__main__":
    main()
