"""Benchmark: pod-node pairs scored per second (BASELINE.md config 4 shape).

Runs the full sequential-commit scheduling scan (10k pods x 5k nodes,
every pod x node pair filtered AND scored by every enabled plugin) and the
one-shot batch evaluation, on whatever jax default backend is live (TPU
under the driver).  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N/50000}
Baseline: >= 50k pairs/sec north star (BASELINE.json).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=10_000)
    ap.add_argument("--nodes", type=int, default=5_000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import jax

    t0 = time.perf_counter()
    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer
    from tests.helpers import random_cluster

    nodes, pods = random_cluster(
        args.seed, n_nodes=args.nodes, n_pods=args.pods, bound_fraction=0.0
    )
    t1 = time.perf_counter()
    feats = Featurizer().featurize(nodes, pods)
    t2 = time.perf_counter()
    print(
        f"built {args.pods} pods x {args.nodes} nodes on {jax.devices()[0].platform}; "
        f"gen {t1-t0:.1f}s featurize {t2-t1:.1f}s; padded "
        f"P={feats.pods.valid.shape[0]} N={feats.nodes.padded}",
        file=sys.stderr,
    )

    def plugins():
        return default_plugins(feats)

    pairs = args.pods * args.nodes

    # Sequential-commit scan (the real scheduling semantics) — headline.
    eng = Engine(feats, plugins(), record="selection")
    eng.schedule()  # compile + warmup
    times = []
    for _ in range(args.repeats):
        t = time.perf_counter()
        res, _state = eng.schedule()
        times.append(time.perf_counter() - t)
    sched_s = min(times)
    sched_pairs = pairs / sched_s

    # One-shot batch evaluation, record="full": materializes every filter
    # reason / raw score / final score matrix (the product's recorded
    # results), unlike the selection-only scan above.
    engb = Engine(feats, plugins(), record="full")
    engb.evaluate_batch()
    times = []
    for _ in range(args.repeats):
        t = time.perf_counter()
        engb.evaluate_batch()
        times.append(time.perf_counter() - t)
    batch_s = min(times)
    batch_pairs = pairs / batch_s

    n_sched = int((res.selected >= 0).sum())
    print(
        f"scan {sched_s*1e3:.1f}ms ({sched_pairs/1e6:.1f}M pairs/s, {n_sched} placed), "
        f"batch {batch_s*1e3:.1f}ms ({batch_pairs/1e6:.1f}M pairs/s)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "sched_pairs_per_sec",
                "value": round(sched_pairs),
                "unit": "pod-node pairs/s (sequential-commit scan, 10k pods x 5k nodes)",
                "vs_baseline": round(sched_pairs / 50_000, 2),
                "batch_pairs_per_sec": round(batch_pairs),
                "pods_scheduled": n_sched,
                "platform": jax.devices()[0].platform,
            }
        )
    )


if __name__ == "__main__":
    main()
